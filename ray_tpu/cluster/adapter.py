"""Cluster adapter: wires a local ``DriverRuntime`` into the GCS cluster.

Role analog: the reference core worker's GCS client + raylet client +
object directory stack (``src/ray/gcs/gcs_client/gcs_client.h:66``,
``ownership_based_object_directory.h``). One adapter per process that hosts
a runtime (the user driver and every node daemon). Responsibilities:

- register this runtime as a node; heartbeat resources;
- publish local object readiness/errors to the global directory;
- watch remote objects and pull their bytes on demand (owner-directed
  fetch: directory -> location -> node daemon pull RPC);
- route task submissions that this node cannot satisfy to a feasible peer
  (driver-side spillback; the reference's raylet lease/spillback role);
- route actor calls to the hosting node;
- react to node death: retry forwarded tasks elsewhere, fail forwarded
  actor calls (``ActorDiedError``), re-execute lost objects' producers
  when lineage allows.
"""

from __future__ import annotations

import logging
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Set

import cloudpickle

from ray_tpu.cluster.rpc import RpcClient, RpcServer
from ray_tpu.core import task_spec as ts
from ray_tpu.core.exceptions import ActorDiedError, WorkerCrashedError
from ray_tpu.core.ids import ActorID, ObjectID

logger = logging.getLogger(__name__)

HEARTBEAT_S = 0.5
NODE_VIEW_TTL_S = 0.5


class ClusterAdapter:
    def __init__(self, gcs_addr: str, authkey: bytes, *,
                 is_scheduler: bool, listen_host: str = "127.0.0.1"):
        self.gcs_addr = gcs_addr
        self.authkey = authkey
        self.is_scheduler = is_scheduler  # only the driver/head spills tasks
        self.listen_host = listen_host
        self.rt = None  # DriverRuntime, set by attach()
        self.node_id: bytes = b""
        self.gcs = RpcClient(gcs_addr, authkey, on_push=self._on_push,
                             reconnect=True,
                             on_reconnect=self._on_gcs_reconnect)
        self._peers: Dict[bytes, RpcClient] = {}
        self._peer_addrs: Dict[bytes, str] = {}
        self._peers_lock = threading.Lock()
        self._watched: Set[bytes] = set()
        self._watch_lock = threading.Lock()
        self._fetching: Set[bytes] = set()
        # forwarded work for failure handling: node_id -> {task_id: spec}
        self._forwarded: Dict[bytes, Dict[bytes, dict]] = {}
        # first return-id -> (node_id, task_id): completion of that object
        # retires the forwarded entry so node death doesn't retry done work
        self._fwd_by_oid: Dict[bytes, tuple] = {}
        self._forwarded_lock = threading.Lock()
        self._remote_actors: Dict[bytes, bytes] = {}  # actor_id -> node_id
        self._node_view: List[dict] = []
        self._node_view_ts = 0.0
        self._spread_rr = 0
        self._stop = threading.Event()
        self.server: Optional[RpcServer] = None
        # All watch/deliver/fetch work runs here, NEVER on the RpcClient
        # reader thread (a blocking gcs.call from the reader thread can
        # never see its own reply) and never on a worker-pipe receiver
        # thread (which must keep demuxing results).
        self._io = ThreadPoolExecutor(max_workers=8,
                                      thread_name_prefix="cluster-io")
        # fn publishes get their own lane: queued behind saturated fetch
        # work they could exceed the consumer's fetch_fn poll window
        self._publish_io = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="cluster-publish")

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def attach(self, rt) -> None:
        """Register ``rt`` as a cluster node and start serving peers."""
        self.rt = rt
        self.node_id = rt.node_id.binary()
        rt.cluster = self
        rt.gcs.on_object_ready = self._publish_ready
        rt.gcs.on_object_error = self._publish_error
        self.server = RpcServer(self.listen_host, 0, self.authkey,
                                self._serve_peer)
        self._register()
        threading.Thread(target=self._heartbeat_loop, daemon=True,
                         name="cluster-heartbeat").start()

    def close(self) -> None:
        self._stop.set()
        try:
            self.gcs.cast("node_drain", self.node_id)
        except Exception:
            pass
        if self.server is not None:
            self.server.close()
        with self._peers_lock:
            peers = list(self._peers.values())
            self._peers.clear()
        for p in peers:
            p.close()
        self.gcs.close()
        self._io.shutdown(wait=False)
        self._publish_io.shutdown(wait=False)

    def _heartbeat_loop(self):
        while not self._stop.wait(HEARTBEAT_S):
            try:
                with self.rt.lock:
                    avail = dict(self.rt.avail)
                    depth = len(self.rt.ready_tasks)
                known = self.gcs.call("node_heartbeat", self.node_id, avail,
                                      depth, timeout=5)
                if known is False:
                    # a restarted GCS lost the (non-durable) node table:
                    # re-register + re-subscribe (GCS FT path)
                    self._register()
            except Exception:
                pass

    def _register(self):
        self.gcs.call("subscribe", "nodes", timeout=10)
        self.gcs.call("subscribe", "objects", timeout=10)
        self.gcs.call("node_register", self.node_id, self.server.addr,
                      self.rt.resources("total"), self.is_scheduler,
                      timeout=10)
        self._node_view_ts = 0.0

    def _on_gcs_reconnect(self):
        try:
            self._register()
        except Exception:
            pass

    # ------------------------------------------------------------------
    # peer RPC service (what other nodes may ask of this one)
    # ------------------------------------------------------------------

    def _serve_peer(self, method: str, args: tuple, ctx) -> Any:
        if method == "submit_spec":
            self.rt.submit_spec(args[0])
            return True
        if method == "submit_actor_spec":
            self.rt.submit_actor_task(args[0])
            return True
        if method == "pull_object":
            return self._serve_pull(args[0])
        if method == "kill_actor":
            self.rt.kill_actor(args[0], args[1])
            return True
        if method == "cancel_task":
            self.rt.cancel_task(ObjectID(args[0]))
            return True
        if method == "ping":
            return "pong"
        raise AttributeError(f"node: unknown method {method!r}")

    def _serve_pull(self, oid_b: bytes):
        oid = ObjectID(oid_b)
        st = self.rt.gcs.object_state(oid)
        if st is not None and st.status == "ERROR":
            return ("e", st.error)
        if st is not None and st.status == "READY" and st.inline is not None:
            return ("i", st.inline)
        raw = self.rt.store.get_raw(oid)
        if raw is not None:
            return ("s", raw)
        # segment gone (evicted/deleted behind the directory's back)
        self.gcs.cast("obj_forget_location", oid_b, self.node_id)
        return None

    # ------------------------------------------------------------------
    # object directory: publish + watch + fetch
    # ------------------------------------------------------------------

    def _publish_ready(self, oid: ObjectID, inline: Optional[bytes],
                       size: int):
        self.gcs.cast("obj_ready", oid.binary(), inline, self.node_id, size)

    def _publish_error(self, oid: ObjectID, err: bytes):
        self.gcs.cast("obj_error", oid.binary(), err)

    def watch_many(self, oids) -> None:
        """Subscribe to global terminal state for objects not yet terminal
        locally; delivery marks them ready/error in the local gcs (pulling
        segment bytes from the owning node when needed). Non-blocking: the
        initial state query runs on the adapter's io pool so hot dispatch
        paths (worker-pipe receivers) never wait on the network."""
        fresh = []
        with self._watch_lock:
            for o in oids:
                b = o.binary() if isinstance(o, ObjectID) else o
                if b not in self._watched:
                    self._watched.add(b)
                    fresh.append(b)
        for b in fresh:
            # subscribe-then-query closes the race where the object turned
            # terminal between our local check and the subscription
            self._io.submit(self._initial_query, b)

    def _initial_query(self, b: bytes):
        try:
            state = self.gcs.call("obj_state", b, timeout=30)
        except Exception:
            return  # the push subscription remains our signal
        if state is not None and state["status"] in ("READY", "ERROR"):
            self._deliver(b, state)

    def _on_push(self, channel: str, payload):
        # runs on the RpcClient reader thread: hand everything that might
        # issue RPCs to the io pool. Object pushes are notifications only
        # (no payload bytes); interested adapters fetch the state.
        if channel == "objects":
            b = payload["oid"]
            with self._watch_lock:
                interested = b in self._watched
            if interested:
                self._io.submit(self._initial_query, b)
        elif channel == "nodes":
            if payload.get("event") == "down":
                self._io.submit(self._node_down, payload)
            self._node_view_ts = 0.0  # invalidate the scheduler view

    def _deliver(self, oid_b: bytes, state: dict):
        """Apply a terminal global state to the local gcs (fetch if big)."""
        with self._forwarded_lock:
            ent = self._fwd_by_oid.pop(oid_b, None)
            if ent is not None:
                self._forwarded.get(ent[0], {}).pop(ent[1], None)
        oid = ObjectID(oid_b)
        st = self.rt.gcs.object_state(oid)
        if st is not None and st.status in ("READY", "ERROR"):
            self._unwatch(oid_b)
            return
        if state["status"] == "ERROR":
            self.rt.gcs.mark_error(oid, state["error"], _local_only=True)
            self._unwatch(oid_b)
            return
        if state["inline"] is not None:
            self.rt.gcs.mark_ready(oid, inline=state["inline"],
                                   _local_only=True)
            self._unwatch(oid_b)
            return
        if self.node_id in state["locations"]:
            # we hold the segment already (e.g. worker-produced locally)
            self.rt.gcs.mark_ready(oid, size=state["size"], _local_only=True)
            self._unwatch(oid_b)
            return
        with self._watch_lock:
            if oid_b in self._fetching:
                return
            self._fetching.add(oid_b)
        try:
            self._fetch(oid, state)
        finally:
            with self._watch_lock:
                self._fetching.discard(oid_b)

    def _fetch(self, oid: ObjectID, state: dict):
        """Owner-directed pull: try each advertised location."""
        for node_id in state["locations"]:
            peer = self._peer(node_id)
            if peer is None:
                continue
            try:
                payload = peer.call("pull_object", oid.binary(), timeout=60)
            except Exception:
                continue
            if payload is None:
                continue
            kind, blob = payload
            if kind == "e":
                self.rt.gcs.mark_error(oid, blob, _local_only=True)
            elif kind == "i":
                self.rt.gcs.mark_ready(oid, inline=blob, _local_only=True)
            else:
                if not self.rt.store.contains(oid):
                    self.rt.store.put_serialized(oid, blob)
                # local copy now exists: advertise it so future readers
                # have a second source (reference push-on-pull behavior)
                self.rt.gcs.mark_ready(oid, size=len(blob))
            self._unwatch(oid.binary())
            return
        # no location answered: wait for re-execution/another location via
        # the still-active subscription (lineage reconstruction path)
        logger.warning("fetch of %s found no live location", oid.hex()[:8])

    def _unwatch(self, oid_b: bytes):
        with self._watch_lock:
            self._watched.discard(oid_b)

    # ------------------------------------------------------------------
    # scheduling (driver/head only)
    # ------------------------------------------------------------------

    def _nodes(self) -> List[dict]:
        now = time.monotonic()
        if now - self._node_view_ts > NODE_VIEW_TTL_S:
            try:
                self._node_view = self.gcs.call("node_list", timeout=5)
                self._node_view_ts = now
            except Exception:
                pass
        return self._node_view

    def maybe_forward_task(self, spec: dict) -> bool:
        """Decide placement for a task/actor-create spec. Returns True when
        the spec was forwarded to a peer node (caller only tracks refs).
        Placement is resource-feasibility first-fit with spillback;
        NodeAffinity / SPREAD strategies are honored (reference
        scheduling_strategies.py); dependency locality is future work
        (the reference's hybrid policy weighs both)."""
        if not self.is_scheduler:
            # daemons execute what they're given — EXCEPT nested
            # submissions this node can never satisfy, which would queue
            # forever; those spill to a feasible peer (reference raylet
            # spillback, hybrid_scheduling_policy.h:50 role). Node
            # affinity binds nested submissions too.
            strat = spec.get("strategy")
            if strat is not None and strat[0] == "node_affinity":
                out = self._place_node_affinity(spec, strat[1], strat[2])
                if out is not None:
                    return out
            return self._spill_if_infeasible(spec)
        if spec.get("pg") is not None:
            return False  # placement groups are node-local (for now)
        res = spec.get("resources") or {}
        strat = spec.get("strategy")
        if strat is not None and strat[0] == "node_affinity":
            out = self._place_node_affinity(spec, strat[1], strat[2])
            if out is not None:
                return out
            # soft affinity to a dead node: fall through to normal placement
        elif strat is not None and strat[0] == "spread":
            return self._place_spread(spec, res)
        with self.rt.lock:
            local_total_ok = all(
                self.rt.total.get(k, 0.0) >= v for k, v in res.items())
            local_avail_ok = all(
                self.rt.avail.get(k, 0.0) >= v for k, v in res.items())
        if local_avail_ok:
            return False  # local fast path
        candidates, with_avail = self._feasible_peers(res)
        if not candidates:
            return False  # infeasible everywhere -> queue locally
        if local_total_ok and not with_avail:
            return False  # locally feasible soon; nobody free now anyway
        return self._forward_to_best(with_avail or candidates, res, spec)

    def _feasible_peers(self, res: Dict[str, float]):
        """(feasible-by-total, also-free-now) peer views for ``res``."""
        candidates = [
            n for n in self._nodes()
            if n["alive"] and n["node_id"] != self.node_id
            and all(n["resources"].get(k, 0.0) >= v for k, v in res.items())
        ]
        with_avail = [
            n for n in candidates
            if all(n["avail"].get(k, 0.0) >= v for k, v in res.items())
        ]
        return candidates, with_avail

    def _forward_to_best(self, picks, res: Dict[str, float],
                         spec: dict) -> bool:
        target = picks[0]
        # decrement the cached view so a burst of submissions spreads across
        # peers instead of piling onto one node until the next heartbeat
        for k, v in res.items():
            target["avail"][k] = target["avail"].get(k, 0.0) - v
        return self._forward(target["node_id"], spec)

    def _spill_if_infeasible(self, spec: dict) -> bool:
        if spec.get("pg") is not None:
            return False
        res = spec.get("resources") or {}
        with self.rt.lock:
            if all(self.rt.total.get(k, 0.0) >= v for k, v in res.items()):
                return False  # feasible here: run/queue locally
        candidates, with_avail = self._feasible_peers(res)
        picks = (with_avail or candidates)
        if not picks:
            return False  # nowhere feasible: queue locally (matches head)
        return self._forward_to_best(picks, res, spec)

    def _place_node_affinity(self, spec: dict, node_id: bytes, soft: bool):
        """Pin to a node (reference NodeAffinitySchedulingStrategy). Hard
        affinity to a dead/unknown node fails the task; soft falls back to
        normal placement (``None`` = caller continues the normal path)."""
        if node_id == self.node_id:
            return False  # pinned here: run locally
        target = next((n for n in self._nodes()
                       if n["node_id"] == node_id and n["alive"]), None)
        if target is None:
            if soft:
                return None  # soft: let normal placement handle it
            self._fail_returns(spec, WorkerCrashedError(
                f"node affinity target {node_id.hex()[:8]} is not alive"))
            return True
        return self._forward(node_id, spec)

    def _place_spread(self, spec: dict, res: Dict[str, float]) -> bool:
        """Round-robin over feasible nodes including this one (reference
        SPREAD strategy)."""
        feasible = [n for n in self._nodes() if n["alive"] and all(
            n["resources"].get(k, 0.0) >= v for k, v in res.items())]
        with self.rt.lock:
            local_ok = all(self.rt.total.get(k, 0.0) >= v
                           for k, v in res.items())
        slots = ([{"node_id": self.node_id}] if local_ok else []) + [
            n for n in feasible if n["node_id"] != self.node_id]
        if not slots:
            return False
        pick = slots[self._spread_rr % len(slots)]
        self._spread_rr += 1
        if pick["node_id"] == self.node_id:
            return False
        return self._forward(pick["node_id"], spec)

    def _forward(self, node_id: bytes, spec: dict) -> bool:
        peer = self._peer(node_id)
        if peer is None:
            return False
        if spec.get("stream_backpressure"):
            # permit waits would land on the EXECUTING node while consumer
            # acks land here — cross-node permit plumbing doesn't exist
            # yet, so a forwarded producer would park forever. Stream
            # unthrottled instead.
            spec = dict(spec)
            spec.pop("stream_backpressure")
        try:
            peer.call("submit_spec", spec, timeout=30)
        except Exception:
            return False
        with self._forwarded_lock:
            self._forwarded.setdefault(node_id, {})[spec["task_id"]] = spec
            if spec["return_ids"]:
                self._fwd_by_oid[spec["return_ids"][0]] = (node_id,
                                                           spec["task_id"])
        aid = spec.get("actor_id")
        if aid:
            self._remote_actors[aid] = node_id
        self.watch_many([ObjectID(b) for b in spec["return_ids"]])
        return True

    def route_actor_call(self, spec: dict) -> bool:
        """Forward an actor method call to the hosting node. Returns True
        when handled (including terminal failure)."""
        aid = spec["actor_id"]
        node_id = self._remote_actors.get(aid)
        if node_id is None:
            rec = None
            try:
                rec = self.gcs.call("actor_get", aid, timeout=5)
            except Exception:
                pass
            if rec is None:
                return False
            if rec["state"] == "DEAD":
                self._fail_returns(spec, ActorDiedError("actor is dead"))
                return True
            node_id = rec["node_id"]
            if node_id == self.node_id:
                return False  # ours after all (race with registration)
            self._remote_actors[aid] = node_id
        for rid in spec["return_ids"]:
            self.rt.gcs.ensure_object(ObjectID(rid))
        peer = self._peer(node_id)
        ok = False
        if peer is not None:
            try:
                peer.call("submit_actor_spec", spec, timeout=30)
                ok = True
            except Exception:
                ok = False
        if not ok:
            self._fail_returns(spec, ActorDiedError(
                f"actor's node {node_id.hex()[:8]} unreachable"))
            return True
        with self._forwarded_lock:
            self._forwarded.setdefault(node_id, {})[spec["task_id"]] = spec
            if spec["return_ids"]:
                self._fwd_by_oid[spec["return_ids"][0]] = (node_id,
                                                           spec["task_id"])
        self.watch_many([ObjectID(b) for b in spec["return_ids"]])
        return True

    def _fail_returns(self, spec: dict, exc: Exception):
        err = cloudpickle.dumps(exc)
        for rid in spec["return_ids"]:
            self.rt.gcs.mark_error(ObjectID(rid), err, _local_only=True)

    # ------------------------------------------------------------------
    # actor + name + fn + kv global mirrors
    # ------------------------------------------------------------------

    def kill_remote_actor(self, actor_id: bytes, no_restart: bool):
        node_id = self._remote_actors.get(actor_id)
        if node_id is None:
            try:
                rec = self.gcs.call("actor_get", actor_id, timeout=5)
            except Exception:
                return
            if rec is None:
                return
            node_id = rec["node_id"]
        peer = self._peer(node_id)
        if peer is not None:
            try:
                peer.call("kill_actor", actor_id, no_restart, timeout=10)
            except Exception:
                pass

    def publish_actor(self, actor_id: bytes, name: str):
        self.gcs.cast("actor_register", actor_id, self.node_id, name or "")

    def publish_actor_state(self, actor_id: bytes, state: str):
        self.gcs.cast("actor_update", actor_id, state)

    def lookup_named(self, name: str) -> Optional[bytes]:
        try:
            return self.gcs.call("actor_lookup", name, timeout=5)
        except Exception:
            return None

    def publish_fn(self, h: str, blob: bytes):
        # synchronous: the blob must be globally visible BEFORE any spec
        # referencing it can be forwarded (an async cast races the forward
        # and a remote worker's fn_get can observe not-found)
        try:
            self.gcs.call("fn_put", h, blob, timeout=30)
        except Exception:
            self.gcs.cast("fn_put", h, blob)  # best effort under outage

    def publish_fn_async(self, h: str, blob: bytes):
        """For worker-pipe receiver threads (must not block): a dedicated
        single-thread lane bounds the publish delay under io-pool
        saturation; remote consumers' fetch_fn poll covers the gap."""
        self._publish_io.submit(self.publish_fn, h, blob)

    def fetch_fn(self, h: str, timeout_s: float = 15.0) -> Optional[bytes]:
        """Poll: the publishing driver may still be mid-flight (blobs are
        immutable, so waiting is safe)."""
        deadline = time.monotonic() + timeout_s
        while True:
            try:
                blob = self.gcs.call("fn_get", h, timeout=10)
            except Exception:
                blob = None
            if blob is not None or time.monotonic() >= deadline:
                return blob
            time.sleep(0.1)

    def kv_op(self, op: str, *args):
        """Cluster KV is globally consistent: always through the GCS.

        Pads the optional trailing args (namespace / overwrite) that the
        local ``Gcs`` signatures default.
        """
        full = list(args)
        if op == "put":
            full += ["default", True][len(full) - 2:] if len(full) < 4 else []
        elif op in ("get", "del"):
            if len(full) < 2:
                full.append("default")
        elif op == "keys":
            if len(full) == 0:
                full.append("")
            if len(full) < 2:
                full.append("default")
        return self.gcs.call("kv_" + op, *full, timeout=30)

    def node_info(self) -> List[dict]:
        return [
            {"NodeID": n["node_id"].hex(),
             "Alive": n["alive"], "Resources": dict(n["resources"]),
             "alive": n["alive"]}
            for n in self._nodes()
        ]

    # ------------------------------------------------------------------
    # failure handling
    # ------------------------------------------------------------------

    def _node_down(self, payload: dict):
        node_id = payload["node_id"]
        with self._peers_lock:
            peer = self._peers.pop(node_id, None)
            self._peer_addrs.pop(node_id, None)
        if peer is not None:
            peer.close()
        dead_actors = set(payload.get("dead_actors", []))
        with self._forwarded_lock:
            lost = self._forwarded.pop(node_id, {})
        for task_id, spec in lost.items():
            if spec.get("actor_id") and spec["type"] != ts.ACTOR_CREATE:
                self._fail_returns(spec, ActorDiedError(
                    "actor's node died"))
                continue
            if spec.get("retries_left", 0) > 0 or spec["type"] == ts.ACTOR_CREATE:
                spec = dict(spec)
                if spec.get("retries_left", 0) > 0:
                    spec["retries_left"] -= 1
                logger.info("retrying task %s from dead node %s",
                            task_id.hex()[:8], node_id.hex()[:8])
                self.rt.submit_spec(spec)
            else:
                self._fail_returns(spec, WorkerCrashedError(
                    f"node {node_id.hex()[:8]} died running task"))
        for aid in dead_actors:
            self._remote_actors.pop(aid, None)

    # ------------------------------------------------------------------

    def _peer(self, node_id: bytes) -> Optional[RpcClient]:
        with self._peers_lock:
            peer = self._peers.get(node_id)
        if peer is not None:
            return peer
        addr = self._peer_addrs.get(node_id)
        if addr is None:
            for n in self._nodes():
                if n["node_id"] == node_id and n["alive"]:
                    addr = n["addr"]
                    break
        if not addr:
            return None
        try:
            peer = RpcClient(addr, self.authkey)
        except Exception:
            return None
        with self._peers_lock:
            existing = self._peers.get(node_id)
            if existing is not None:
                peer.close()
                return existing
            self._peers[node_id] = peer
            self._peer_addrs[node_id] = addr
        return peer
