"""Message RPC over ``multiprocessing.connection`` (TCP + authkey).

Role analog: the reference's gRPC plumbing (``src/ray/rpc/grpc_server.h``,
``client_call.h``) — reduced to what the cluster needs: request/reply with
out-of-order completion, one-way casts, and server->client pushes
(pubsub-lite). Wire messages are pickled tuples:

    ("req",  id, method, args)      client -> server, expects a reply
    ("rep",  id, ok, payload)       server -> client
    ("cast", method, args)          client -> server, no reply
    ("push", channel, payload)      server -> client (subscriptions)

Each server connection gets a reader thread; request handlers run on a
shared thread pool so a blocking handler (e.g. a directory wait) never
stalls the connection. TCP (AF_INET) so the same code carries multi-host;
tests run everything on localhost.

Wire versioning (reference role: the protobuf schema in
``src/ray/protobuf/`` gives every message a versioned contract): the
client's FIRST message is ``("hello", (major, minor))``; the server
replies ``("hello_ack", (major, minor))``. A major mismatch refuses the
connection with :class:`WireVersionError` — a clear error at connect
time instead of an unpickling crash mid-conversation when heterogeneous
node versions meet. Minor bumps are additive (new methods/fields) and
interoperate.
"""

from __future__ import annotations

import itertools
import pickle
import socket
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from multiprocessing.connection import Client as _MpClient
from multiprocessing.connection import Listener as _MpListener
from multiprocessing.reduction import ForkingPickler
from time import perf_counter
from typing import Any, Callable, Dict, Optional, Tuple

WIRE_VERSION: Tuple[int, int] = (1, 0)

#: transport instrumentation (defs in util/metric_defs.py): framed bytes
#: both directions, server queue-wait (socket read -> handler start, the
#: GCS accept-loop contention signal), client reconnects/timeouts.
#: metric_defs.get is a cached fast path that survives clear_registry,
#: so the accessor just rebuilds; tag keys stay pre-sorted.
_REQ_KEY = (("kind", "req"),)
_CAST_KEY = (("kind", "cast"),)


def _rpc_metrics():
    from ray_tpu.util import metric_defs as md

    return {"sent": md.get("rtpu_rpc_sent_bytes_total"),
            "recv": md.get("rtpu_rpc_recv_bytes_total"),
            "requests": md.get("rtpu_rpc_server_requests_total"),
            "queue_wait": md.get("rtpu_rpc_server_queue_wait_seconds"),
            "reconnects": md.get("rtpu_rpc_client_reconnects_total"),
            "reconnect_attempts": md.get(
                "rtpu_rpc_client_reconnect_attempts_total"),
            "timeouts": md.get("rtpu_rpc_client_timeouts_total")}


def _send_framed(conn, send_lock, msg) -> None:
    """Pickle-then-send_bytes (what ``conn.send`` does internally — same
    reducer, no extra copy) so the framed size feeds the byte counters."""
    buf = ForkingPickler.dumps(msg)
    with send_lock:
        conn.send_bytes(buf)
    try:
        _rpc_metrics()["sent"]._inc_key((), len(buf))
    except Exception:
        pass


def _recv_framed(conn):
    buf = conn.recv_bytes()
    try:
        _rpc_metrics()["recv"]._inc_key((), len(buf))
    except Exception:
        pass
    return pickle.loads(buf)


class WireVersionError(ConnectionError):
    """Peer speaks an incompatible wire major version (terminal)."""


class WireHandshakeTimeout(ConnectionError):
    """No handshake ack in time — transient (loaded box, restart herd),
    NOT a version mismatch; reconnect paths must keep retrying."""


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def parse_addr(addr: str) -> Tuple[str, int]:
    host, port = addr.rsplit(":", 1)
    return host, int(port)


class RpcServer:
    """Serves ``handler(method, args, ctx) -> payload`` over TCP.

    ``ctx`` is the per-connection :class:`ServerConn`, so handlers can
    subscribe the caller to push channels or identify it across calls.
    """

    def __init__(self, host: str, port: int, authkey: bytes,
                 handler: Callable[[str, tuple, "ServerConn"], Any],
                 max_workers: int = 16):
        self._listener = _MpListener((host, port), family="AF_INET",
                                     authkey=authkey)
        self.addr = f"{host}:{self._listener.address[1]}"
        self._handler = handler
        self._pool = ThreadPoolExecutor(max_workers=max_workers,
                                        thread_name_prefix="rpc")
        self._conns: Dict[int, "ServerConn"] = {}
        self._lock = threading.Lock()
        self._closed = False
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="rpc-accept")
        self._accept_thread.start()

    def _accept_loop(self):
        counter = itertools.count()
        while not self._closed:
            try:
                raw = self._listener.accept()
            except (OSError, EOFError):
                return
            conn = ServerConn(next(counter), raw, self)
            with self._lock:
                self._conns[conn.conn_id] = conn
            threading.Thread(target=conn.reader_loop, daemon=True,
                             name=f"rpc-conn-{conn.conn_id}").start()

    def _drop_conn(self, conn: "ServerConn"):
        with self._lock:
            self._conns.pop(conn.conn_id, None)

    def broadcast(self, channel: str, payload: Any,
                  only_subscribed: bool = True) -> int:
        """Push to subscribers; returns the delivery count (fanout)."""
        with self._lock:
            conns = list(self._conns.values())
        n = 0
        for c in conns:
            if only_subscribed and channel not in c.subscriptions:
                continue
            c.push(channel, payload)
            n += 1
        return n

    def close(self):
        self._closed = True
        try:
            self._listener.close()
        except Exception:
            pass
        with self._lock:
            conns = list(self._conns.values())
        for c in conns:
            c.close()
        self._pool.shutdown(wait=False)


class ServerConn:
    def __init__(self, conn_id: int, raw, server: RpcServer):
        self.conn_id = conn_id
        self.raw = raw
        self.server = server
        self.send_lock = threading.Lock()
        self.subscriptions: set = set()
        self.meta: Dict[str, Any] = {}  # handler scratch (e.g. node_id)
        self.on_close: Optional[Callable[["ServerConn"], None]] = None

    def reader_loop(self):
        # handshake: first message must be a compatible hello
        try:
            first = _recv_framed(self.raw)
        except (EOFError, OSError, TypeError, ValueError):
            first = None
        try:
            ok_shape = (isinstance(first, tuple) and len(first) >= 2
                        and first[0] == "hello")
            peer_version = tuple(first[1]) if ok_shape else ()
            ok_shape = ok_shape and len(peer_version) >= 1 and all(
                isinstance(v, int) for v in peer_version)
        except TypeError:
            ok_shape, peer_version = False, ()
        if not ok_shape:
            self._send(("hello_nack", WIRE_VERSION,
                        "expected hello as first message"))
            self.close()
            self.server._drop_conn(self)
            return
        if peer_version[0] != WIRE_VERSION[0]:
            self._send(("hello_nack", WIRE_VERSION,
                        f"wire major {peer_version[0]} != {WIRE_VERSION[0]}"))
            self.close()
            self.server._drop_conn(self)
            return
        self.meta["wire_version"] = peer_version
        self._send(("hello_ack", WIRE_VERSION))
        m = _rpc_metrics()
        while True:
            try:
                msg = _recv_framed(self.raw)
            except (EOFError, OSError, TypeError, ValueError):
                break
            kind = msg[0]
            if kind == "req":
                _, req_id, method, args = msg
                m["requests"]._inc_key(_REQ_KEY)
                self.server._pool.submit(self._run, req_id, method, args,
                                         perf_counter())
            elif kind == "cast":
                _, method, args = msg
                m["requests"]._inc_key(_CAST_KEY)
                self.server._pool.submit(self._run, None, method, args,
                                         perf_counter())
        self.server._drop_conn(self)
        cb = self.on_close
        if cb is not None:
            try:
                cb(self)
            except Exception:
                pass

    def _run(self, req_id: Optional[int], method: str, args: tuple,
             enq_ts: Optional[float] = None):
        if enq_ts is not None:
            # thread-pool queue wait: socket read -> handler start. Tail
            # growth here means the server's 16 handler threads (or 2
            # host vCPUs) are saturated — the "is the GCS the
            # bottleneck?" signal.
            try:
                _rpc_metrics()["queue_wait"]._observe_key(
                    (), perf_counter() - enq_ts)
            except Exception:
                pass
        try:
            from ray_tpu.util import failpoints

            if failpoints.hit("rpc.server.dispatch", method):
                return  # chaos: swallow the request; the caller times out
            payload = self.server._handler(method, args, self)
            ok = True
        except BaseException as e:  # noqa: BLE001 — shipped to caller
            payload, ok = e, False
        if req_id is not None:
            self._send(("rep", req_id, ok, payload))

    def push(self, channel: str, payload: Any):
        self._send(("push", channel, payload))

    def _send(self, msg):
        try:
            _send_framed(self.raw, self.send_lock, msg)
        except (OSError, BrokenPipeError, ValueError):
            pass

    def close(self):
        try:
            self.raw.close()
        except Exception:
            pass


def _client_handshake(conn, addr: str, timeout: float = 10.0):
    """Exchange hello/hello_ack; raise :class:`WireVersionError` when the
    server refuses (major mismatch) or doesn't speak the handshake."""
    conn.send(("hello", WIRE_VERSION))
    if not conn.poll(timeout):
        raise WireHandshakeTimeout(
            f"server at {addr} sent no handshake ack within {timeout}s")
    reply = conn.recv()
    if (not isinstance(reply, tuple) or not reply
            or reply[0] != "hello_ack"):
        detail = (reply[2] if isinstance(reply, tuple) and len(reply) > 2
                  else reply)
        raise WireVersionError(
            f"server at {addr} refused wire version {WIRE_VERSION}: {detail}")
    return tuple(reply[1])


class RpcClient:
    """Client with one reader thread demuxing replies and pushes.

    ``reconnect=True`` keeps retrying the server after a drop (in-flight
    calls still fail — callers own retries) and fires ``on_reconnect`` so
    owners can re-subscribe/re-register; this is what lets node daemons
    survive a GCS restart (reference GCS fault tolerance role).
    """

    def __init__(self, addr: str, authkey: bytes,
                 on_push: Optional[Callable[[str, Any], None]] = None,
                 on_disconnect: Optional[Callable[[], None]] = None,
                 reconnect: bool = False,
                 on_reconnect: Optional[Callable[[], None]] = None):
        host, port = parse_addr(addr)
        self.addr = addr
        self._hostport = (host, port)
        self._authkey = authkey
        self._conn = _MpClient((host, port), family="AF_INET",
                               authkey=authkey)
        self.server_wire_version = _client_handshake(self._conn, addr)
        self._send_lock = threading.Lock()
        self._pending: Dict[int, tuple] = {}  # id -> (event, box)
        self._pending_lock = threading.Lock()
        self._ids = itertools.count(1)
        self._on_push = on_push
        self._on_disconnect = on_disconnect
        self._reconnect = reconnect
        self._on_reconnect = on_reconnect
        self._closed = False
        threading.Thread(target=self._reader_loop, daemon=True,
                         name="rpc-client-reader").start()

    def _reader_loop(self):
        while not self._closed:
            self._read_until_drop()
            with self._pending_lock:
                pending = list(self._pending.values())
                self._pending.clear()
            for ev, box in pending:
                box[:] = [False,
                          ConnectionError(f"rpc connection to {self.addr} lost")]
                ev.set()
            if self._closed or not self._reconnect:
                break
            if not self._try_reconnect():
                break
            if self._on_reconnect is not None:
                # NEVER run the callback on this thread: replies to any RPC
                # it issues are demuxed HERE, so a synchronous callback
                # would deadlock its own calls into timeouts
                def _cb():
                    try:
                        self._on_reconnect()
                    except Exception:
                        pass

                threading.Thread(target=_cb, daemon=True,
                                 name="rpc-reconnect-cb").start()
        if not self._closed and self._on_disconnect is not None:
            try:
                self._on_disconnect()
            except Exception:
                pass

    def _read_until_drop(self):
        while True:
            try:
                msg = _recv_framed(self._conn)
            except (EOFError, OSError, TypeError, ValueError):
                # TypeError/ValueError: multiprocessing internals raise
                # these when the fd is closed from under a blocked recv
                return
            if msg[0] == "rep":
                _, req_id, ok, payload = msg
                with self._pending_lock:
                    ent = self._pending.pop(req_id, None)
                if ent is not None:
                    ent[1][:] = [ok, payload]
                    ent[0].set()
            elif msg[0] == "push" and self._on_push is not None:
                try:
                    self._on_push(msg[1], msg[2])
                except Exception:
                    pass

    def _try_reconnect(self, max_wait_s: float = 120.0) -> bool:
        deadline = time.monotonic() + max_wait_s
        delay = 0.2
        m = _rpc_metrics()
        while not self._closed and time.monotonic() < deadline:
            try:
                m["reconnect_attempts"]._inc_key(())
                conn = _MpClient(self._hostport, family="AF_INET",
                                 authkey=self._authkey)
                try:
                    _client_handshake(conn, self.addr)
                except WireVersionError:
                    # a major mismatch won't heal by retrying
                    try:
                        conn.close()
                    except Exception:
                        pass
                    return False
                with self._send_lock:
                    # calls that raced the outage and sent into the dying
                    # socket would otherwise wait out their full timeout
                    # (or forever): fail them now so callers retry
                    with self._pending_lock:
                        stale = list(self._pending.values())
                        self._pending.clear()
                    for ev, box in stale:
                        box[:] = [False, ConnectionError(
                            f"rpc connection to {self.addr} was replaced")]
                        ev.set()
                    old, self._conn = self._conn, conn
                try:
                    old.close()  # don't leak one fd per outage
                except Exception:
                    pass
                m["reconnects"]._inc_key(())
                return True
            except Exception:
                time.sleep(delay)
                delay = min(delay * 1.6, 3.0)
        return False

    def call(self, method: str, *args, timeout: Optional[float] = None) -> Any:
        """Request/reply. ``timeout=None`` applies the default deadline
        (``RTPU_RPC_DEFAULT_TIMEOUT_S``): an un-deadlined call into a
        wedged peer would park this thread forever, and every such parked
        thread is a recovery hole (chaos ISSUE 5). Call sites that truly
        need a longer wait pass it explicitly; a non-positive configured
        default restores the unbounded wait."""
        if timeout is None:
            from ray_tpu import config as _cfg

            t = float(_cfg.get("rpc_default_timeout_s"))
            timeout = t if t > 0 else None
        req_id = next(self._ids)
        ev = threading.Event()
        box: list = []
        with self._pending_lock:
            self._pending[req_id] = (ev, box)
        self._send_counted(("req", req_id, method, args))
        if not ev.wait(timeout):
            with self._pending_lock:
                self._pending.pop(req_id, None)
            try:
                _rpc_metrics()["timeouts"]._inc_key(())
            except Exception:
                pass
            raise TimeoutError(f"rpc {method} timed out after {timeout}s")
        ok, payload = box
        if not ok:
            raise payload
        return payload

    def _send_counted(self, msg) -> None:
        from ray_tpu.util import failpoints

        if failpoints.hit("rpc.client.send",
                          msg[2] if msg[0] == "req" else msg[1]):
            return  # chaos: drop this request/cast on the floor
        # self._conn must be read INSIDE the send lock: the reconnect
        # path swaps it under the same lock
        buf = ForkingPickler.dumps(msg)
        with self._send_lock:
            self._conn.send_bytes(buf)
        try:
            _rpc_metrics()["sent"]._inc_key((), len(buf))
        except Exception:
            pass

    def cast(self, method: str, *args) -> None:
        try:
            self._send_counted(("cast", method, args))
        except (OSError, BrokenPipeError, ValueError):
            pass

    def close(self):
        self._closed = True
        try:
            self._conn.close()
        except Exception:
            pass
