"""Test/dev cluster: GCS + extra node daemons as local subprocesses.

Role analog: ``python/ray/cluster_utils.py:135`` (``Cluster``) whose
``add_node`` (``:201``) boots extra raylets as separate processes on one
machine — the reference's standard way to test multi-node scheduling,
transfer, and failover without real machines.

Usage::

    cluster = Cluster()                      # starts a GCS process
    cluster.add_node(resources={"worker": 1})
    ray_tpu.init(address=cluster.address)    # driver joins as head node
    ...
    cluster.shutdown()
"""

from __future__ import annotations

import os
import subprocess
import sys
import time
import uuid
from typing import Dict, List, Optional

from ray_tpu.cluster.rpc import RpcClient, free_port


class Cluster:
    def __init__(self, node_timeout_s: float = 8.0,
                 gcs_snapshot: Optional[str] = None):
        self.authkey = uuid.uuid4().hex[:16]
        self._node_timeout_s = node_timeout_s
        self._gcs_snapshot = gcs_snapshot
        self._procs: List[subprocess.Popen] = []
        self._node_procs: Dict[int, subprocess.Popen] = {}
        self._next_node = 0
        # free_port() is inherently TOCTOU: under a loaded test suite the
        # chosen port can be grabbed (or still be held by a dying server
        # from a previous cluster) before our GCS binds it, and the first
        # client then talks to a foreign listener (observed as OSError
        # "bad message length" during the auth challenge). First boot has
        # no published address yet, so just retry on a fresh port.
        last = None
        for attempt in range(3):
            self._port = free_port()
            self.address = f"127.0.0.1:{self._port}"
            self._gcs_proc = self._spawn_gcs()
            try:
                self._wait_for_gcs()
                # reconnect=True: wait_for_nodes/list_nodes retry polls
                # through transient drops — without it the first drop
                # kills the client permanently and every retry spins on
                # a dead socket
                self._client = RpcClient(self.address,
                                         self.authkey.encode(),
                                         reconnect=True)
                return
            except Exception as e:
                last = e
                try:
                    self._gcs_proc.kill()
                    self._gcs_proc.wait(timeout=10)
                except Exception:
                    pass
                self._procs.remove(self._gcs_proc)
        raise RuntimeError(f"cluster GCS failed to boot after 3 ports: {last}")

    def _spawn_gcs(self) -> subprocess.Popen:
        cmd = [sys.executable, "-m", "ray_tpu.cluster.gcs_server",
               "--port", str(self._port), "--authkey", self.authkey,
               "--node-timeout", str(self._node_timeout_s)]
        if self._gcs_snapshot:
            cmd += ["--snapshot", self._gcs_snapshot]
        proc = subprocess.Popen(cmd, env=self._env(),
                                stdout=subprocess.DEVNULL,
                                stderr=subprocess.STDOUT)
        self._procs.append(proc)
        return proc

    def restart_gcs(self):
        """Kill + restart the GCS process on the same port (GCS FT test
        path; with a snapshot configured, durable tables survive and
        daemons re-register via heartbeat NACK)."""
        self._gcs_proc.kill()
        self._gcs_proc.wait()
        import time as _t

        _t.sleep(0.2)  # let the port free
        self._gcs_proc = self._spawn_gcs()
        self._wait_for_gcs()
        try:
            self._client.close()
        except Exception:
            pass
        self._client = RpcClient(self.address, self.authkey.encode())

    def _env(self):
        env = dict(os.environ)
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
        # cluster workers are CPU-only by default (same as single-node)
        env.setdefault("JAX_PLATFORMS", "cpu")
        return env

    def _wait_for_gcs(self, timeout: float = 20.0):
        deadline = time.monotonic() + timeout
        last = None
        while time.monotonic() < deadline:
            try:
                c = RpcClient(self.address, self.authkey.encode())
                assert c.call("ping", timeout=2) == "pong"
                c.close()
                return
            except Exception as e:
                last = e
                time.sleep(0.1)
        raise TimeoutError(f"gcs did not come up at {self.address}: {last}")

    def add_node(self, *, num_cpus: float = 2,
                 resources: Optional[Dict[str, float]] = None,
                 labels: Optional[Dict[str, str]] = None,
                 env: Optional[Dict[str, str]] = None,
                 wait: bool = True) -> int:
        """Boot a node daemon subprocess; returns a handle id for kill_node.

        ``env``: extra environment for the daemon (chaos tests arm
        per-daemon failpoints by exporting ``RTPU_FAILPOINTS``)."""
        import json

        node_idx = self._next_node
        self._next_node += 1
        full_env = self._env()
        full_env.update(env or {})
        proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu.cluster.node_daemon",
             "--gcs", self.address, "--authkey", self.authkey,
             "--num-cpus", str(num_cpus),
             "--resources", json.dumps(resources or {}),
             "--labels", json.dumps(labels or {})],
            env=full_env, stdout=subprocess.DEVNULL,
            stderr=subprocess.STDOUT,
        )
        self._node_procs[node_idx] = proc
        self._procs.append(proc)
        if wait:
            want = len([p for p in self._node_procs.values()
                        if p.poll() is None])
            self.wait_for_nodes(want)
        return node_idx

    def wait_for_nodes(self, n_daemons: int, timeout: float = 60.0):
        """Wait until ``n_daemons`` non-head nodes are alive in the GCS.

        The 60s default is an under-load margin, not an expectation: on
        this 2-vCPU box a daemon boot races pytest + watcher probes for
        CPU and the r19 flake log shows registration occasionally taking
        >30s while always completing; the poll also retries OSError —
        a daemon mid-boot can RST the probe connection, which surfaces
        as plain OSError, not its ConnectionError subclass."""
        deadline = time.monotonic() + timeout
        alive = []
        while time.monotonic() < deadline:
            try:
                nodes = self._client.call("node_list", timeout=5)
            except (OSError, TimeoutError):
                # transient GCS connection drop under load: the client
                # reconnects; a poll must retry, not abort the wait
                time.sleep(0.3)
                continue
            alive = [x for x in nodes if x["alive"] and not x["is_head"]]
            if len(alive) >= n_daemons:
                return
            time.sleep(0.1)
        raise TimeoutError(f"only {len(alive)} of {n_daemons} nodes alive")

    def list_nodes(self):
        return self._client.call("node_list", timeout=5)

    def kill_node(self, node_idx: int):
        """SIGKILL a node daemon (failure-injection; reference
        ``RayletKiller`` role)."""
        proc = self._node_procs.get(node_idx)
        if proc is not None and proc.poll() is None:
            proc.kill()
            proc.wait()

    def shutdown(self):
        try:
            self._client.close()
        except Exception:
            pass
        for proc in self._procs:
            if proc.poll() is None:
                proc.terminate()
        deadline = time.monotonic() + 3.0
        for proc in self._procs:
            try:
                proc.wait(max(0.1, deadline - time.monotonic()))
            except Exception:
                proc.kill()
