"""Runtime environments: per-task/actor env_vars, working_dir, py_modules.

Role analog: ``python/ray/runtime_env`` + ``_private/runtime_env/``
(``working_dir.py``, ``py_modules.py``, packaging/URI cache). The image is
fixed (no network), so ``pip``/``conda`` are rejected loudly instead of
silently ignored; ``py_modules`` ships local packages through the GCS KV as
zip blobs the same way the reference uploads working-dir packages to its
GCS package store, with content-addressed caching on both sides.
"""

from __future__ import annotations

import hashlib
import io
import os
import zipfile
from typing import Any, Dict, Optional

_PKG_NAMESPACE = "rtpu_pkg"
_UNSUPPORTED = ("pip", "conda", "container", "uv")


def package_runtime_env(renv: Optional[Dict[str, Any]],
                        runtime) -> Optional[Dict[str, Any]]:
    """Driver-side: turn local ``py_modules`` paths into content-addressed
    KV URIs so any worker on any node can materialize them."""
    if not renv:
        return renv
    for key in _UNSUPPORTED:
        if renv.get(key):
            raise ValueError(
                f"runtime_env[{key!r}] is not supported: the image is fixed "
                f"(no package installation at runtime). Bake dependencies "
                f"into the image or ship pure-python code via py_modules.")
    mods = renv.get("py_modules")
    if not mods:
        return renv
    out = dict(renv)
    uris = []
    for mod in mods:
        path = getattr(mod, "__path__", None)
        if path:  # a module object
            mod = list(path)[0]
        mod = os.path.abspath(str(mod))
        blob = _zip_dir(mod)
        uri = f"pkg-{hashlib.sha256(blob).hexdigest()[:24]}"
        # content-addressed: overwrite=False makes re-uploads free
        runtime.kv_op("put", uri, blob, _PKG_NAMESPACE, False)
        uris.append((uri, os.path.basename(mod)))
    out.pop("py_modules")
    out["py_modules_uris"] = uris
    return out


def _zip_dir(path: str) -> bytes:
    if not os.path.exists(path):
        raise FileNotFoundError(f"py_modules path {path!r} does not exist")
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        if os.path.isfile(path):
            zf.write(path, os.path.basename(path))
        else:
            base = os.path.basename(path)
            for root, _, files in os.walk(path):
                for f in files:
                    if f.endswith(".pyc"):
                        continue
                    full = os.path.join(root, f)
                    rel = os.path.join(base, os.path.relpath(full, path))
                    zf.write(full, rel)
    return buf.getvalue()


def materialize_py_modules(uris, kv_get) -> list:
    """Worker-side: fetch + extract each package (cached by content hash);
    returns the sys.path entries to add."""
    out = []
    cache_root = os.path.join("/tmp", "rtpu-pkgs")
    for uri, _name in uris:
        target = os.path.join(cache_root, uri)
        if not os.path.isdir(target):
            blob = kv_get(uri)
            if blob is None:
                raise RuntimeError(f"py_modules package {uri} not found in KV")
            tmp = target + ".tmp-" + str(os.getpid())
            os.makedirs(tmp, exist_ok=True)
            with zipfile.ZipFile(io.BytesIO(blob)) as zf:
                zf.extractall(tmp)
            try:
                os.rename(tmp, target)  # atomic publish; loser cleans up
            except OSError:
                import shutil

                shutil.rmtree(tmp, ignore_errors=True)
        out.append(target)
    return out
