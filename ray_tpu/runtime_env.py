"""Runtime environments: env_vars, working_dir, py_modules, pip venvs.

Role analog: ``python/ray/runtime_env`` + ``_private/runtime_env/``
(``working_dir.py``, ``py_modules.py``, ``pip.py``, packaging/URI cache).
``py_modules`` ships local packages through the GCS KV as zip blobs the
same way the reference uploads working-dir packages to its GCS package
store, with content-addressed caching on both sides. ``pip`` builds an
isolated site directory per requirements-hash on the node (reference
``pip.py``'s URI-cached virtualenv role, realized as ``pip install
--target`` — workers share one interpreter, so prepending the site dir
is the whole isolation mechanism): the first task needing an env
creates it under an exclusive file lock, later tasks hit the cache, and
workers prepend it for the task's duration. The image has no
network, so pip sources must be reachable offline — pass
``pip_args=["--no-index", "--find-links", <wheel dir>]`` (the test
pattern) or point at an internal index. ``conda``/``container`` remain
rejected loudly (no conda/containers in the image).
"""

from __future__ import annotations

import hashlib
import io
import os
import zipfile
from typing import Any, Dict, Optional

_PKG_NAMESPACE = "rtpu_pkg"
_UNSUPPORTED = ("conda", "container", "uv")
_PIP_ENV_ROOT_VAR = "RTPU_PIP_ENV_DIR"


def package_runtime_env(renv: Optional[Dict[str, Any]],
                        runtime) -> Optional[Dict[str, Any]]:
    """Driver-side: turn local ``py_modules`` paths into content-addressed
    KV URIs so any worker on any node can materialize them."""
    if not renv:
        return renv
    for key in _UNSUPPORTED:
        if renv.get(key):
            raise ValueError(
                f"runtime_env[{key!r}] is not supported. Use "
                f"runtime_env={{'pip': [...]}} for per-task package "
                f"isolation (URI-cached per-requirements site dirs), or "
                f"ship pure-python code via py_modules.")
    out = dict(renv)
    pip = out.pop("pip", None)
    if pip is not None:
        # empty specs raise inside normalize (never silently dropped)
        out["pip_env"] = normalize_pip_env(pip)
    mods = renv.get("py_modules")
    if not mods:
        return out if out != renv else renv
    uris = []
    for mod in mods:
        path = getattr(mod, "__path__", None)
        if path:  # a module object
            mod = list(path)[0]
        mod = os.path.abspath(str(mod))
        blob = _zip_dir(mod)
        uri = f"pkg-{hashlib.sha256(blob).hexdigest()[:24]}"
        # content-addressed: overwrite=False makes re-uploads free
        runtime.kv_op("put", uri, blob, _PKG_NAMESPACE, False)
        uris.append((uri, os.path.basename(mod)))
    out.pop("py_modules")
    out["py_modules_uris"] = uris
    return out


def normalize_pip_env(pip) -> Dict[str, Any]:
    """Canonicalize ``runtime_env["pip"]`` and derive its cache URI.

    Accepts a list of requirement strings, or a dict
    ``{"packages": [...], "pip_args": [...]}``. The URI hashes the SORTED
    requirements, the ORDERED pip_args, and the interpreter version, so
    identical envs share one site dir regardless of package order or
    calling driver.
    """
    import sys

    if isinstance(pip, (list, tuple)):
        packages, pip_args = list(pip), []
    elif isinstance(pip, dict):
        packages = list(pip.get("packages") or [])
        pip_args = list(pip.get("pip_args") or [])
    else:
        raise ValueError(
            f"runtime_env['pip'] must be a list of requirements or a "
            f"dict with 'packages'/'pip_args', got {type(pip).__name__}")
    if not packages:
        raise ValueError("runtime_env['pip'] has no packages")
    # domain-separated sections; pip_args keep their ORDER (flag/value
    # pairs are positional) while packages sort (sets, not sequences)
    key = ("pkgs:" + "\n".join(sorted(str(p) for p in packages))
           + "\x00args:" + "\n".join(str(a) for a in pip_args)
           + f"\x00py{sys.version_info[0]}.{sys.version_info[1]}")
    uri = f"pipenv-{hashlib.sha256(key.encode()).hexdigest()[:24]}"
    return {"uri": uri, "packages": packages, "pip_args": pip_args}


def _pip_env_root() -> str:
    return os.environ.get(_PIP_ENV_ROOT_VAR) or os.path.join(
        "/tmp", "rtpu-pip-envs")


def ensure_pip_env(pip_env: Dict[str, Any]) -> str:
    """Materialize the environment for ``pip_env`` (reference ``pip.py``'s
    URI-cached virtualenv role) and return its site-packages dir.

    The env is a plain ``pip install --target`` site directory — workers
    PREPEND it to sys.path rather than exec-ing a separate interpreter,
    so a full venv skeleton (bin/, pyvenv.cfg) would be dead weight.
    First use on a node installs the requirements under an exclusive
    flock — concurrent workers needing the same env wait for the creator
    rather than racing; every later use is a cache hit gated on the
    ``.ready`` marker (which records the requirements for
    debuggability). Creation failures tear the dir down so a partial env
    can never be mistaken for a cache hit.
    """
    import fcntl
    import subprocess
    import sys

    root = _pip_env_root()
    env_dir = os.path.join(root, pip_env["uri"])
    ready = os.path.join(env_dir, ".ready")
    site = os.path.join(env_dir, "site-packages")
    if os.path.exists(ready):
        return site
    os.makedirs(root, exist_ok=True)
    lock_path = os.path.join(root, pip_env["uri"] + ".lock")
    with open(lock_path, "w") as lock:
        fcntl.flock(lock, fcntl.LOCK_EX)
        try:
            if os.path.exists(ready):   # creator finished while we waited
                return site
            try:
                os.makedirs(site, exist_ok=True)
                cmd = [sys.executable, "-m", "pip", "install",
                       "--quiet", "--target", site,
                       *pip_env.get("pip_args", []),
                       *pip_env["packages"]]
                proc = subprocess.run(cmd, capture_output=True, text=True,
                                      timeout=600)
                if proc.returncode != 0:
                    raise RuntimeError(
                        f"pip install failed for runtime_env "
                        f"{pip_env['uri']}: {proc.stderr[-1000:]}")
                with open(ready, "w") as f:
                    f.write("\n".join(pip_env["packages"]) + "\n")
                return site
            except BaseException:
                import shutil

                shutil.rmtree(env_dir, ignore_errors=True)
                raise
        finally:
            fcntl.flock(lock, fcntl.LOCK_UN)


def _zip_dir(path: str) -> bytes:
    if not os.path.exists(path):
        raise FileNotFoundError(f"py_modules path {path!r} does not exist")
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        if os.path.isfile(path):
            zf.write(path, os.path.basename(path))
        else:
            base = os.path.basename(path)
            for root, _, files in os.walk(path):
                for f in files:
                    if f.endswith(".pyc"):
                        continue
                    full = os.path.join(root, f)
                    rel = os.path.join(base, os.path.relpath(full, path))
                    zf.write(full, rel)
    return buf.getvalue()


def materialize_py_modules(uris, kv_get) -> list:
    """Worker-side: fetch + extract each package (cached by content hash);
    returns the sys.path entries to add."""
    out = []
    cache_root = os.path.join("/tmp", "rtpu-pkgs")
    for uri, _name in uris:
        target = os.path.join(cache_root, uri)
        if not os.path.isdir(target):
            blob = kv_get(uri)
            if blob is None:
                raise RuntimeError(f"py_modules package {uri} not found in KV")
            tmp = target + ".tmp-" + str(os.getpid())
            os.makedirs(tmp, exist_ok=True)
            with zipfile.ZipFile(io.BytesIO(blob)) as zf:
                zf.extractall(tmp)
            try:
                os.rename(tmp, target)  # atomic publish; loser cleans up
            except OSError:
                import shutil

                shutil.rmtree(tmp, ignore_errors=True)
        out.append(target)
    return out
