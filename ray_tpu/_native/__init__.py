"""ctypes bindings for the C++ native runtime components.

Role analog: the reference's Cython bridge (``python/ray/_raylet.pyx``) in
miniature — the native pieces are C++ (``native/``), and Python talks to
them through a flat C API (ctypes; pybind11 isn't in the image). The .so is
built on first use with g++ and cached; every consumer must handle
``load_store_lib() is None`` and fall back to the pure-Python path.
"""

from __future__ import annotations

import ctypes
import errno
import os
import subprocess
import threading
from typing import Optional

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "build", "librtpu_store.so")


def _so_path() -> str:
    """The .so to load. ``RTPU_NATIVE_SO`` overrides the default build
    product — the sanitizer pytest lane points it at
    ``native/build/librtpu_store_asan.so`` (with libasan LD_PRELOADed)
    so the whole Python-facing surface runs instrumented without
    touching the normal artifact. Resolved once per process: the first
    load is cached in ``_lib``."""
    return os.environ.get("RTPU_NATIVE_SO") or _SO_PATH


_lib_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_lib_failed = False
#: the .so loaded but lacks the pipe-engine symbols even after a rebuild
#: attempt — a half-state the tier-1 conftest refuses to run in silently
_lib_stale = False


def _build() -> bool:
    try:
        subprocess.run(
            ["make", "-C", _NATIVE_DIR, "-s"],
            check=True, capture_output=True, timeout=120)
        return os.path.exists(_SO_PATH)
    except Exception:
        return False


def load_store_lib() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native store library, or None."""
    global _lib, _lib_failed, _lib_stale
    with _lib_lock:
        if _lib is not None:
            return _lib
        if _lib_failed:
            return None
        so = _so_path()
        if not os.path.exists(so):
            # never auto-build over an explicit RTPU_NATIVE_SO target —
            # a missing override is a configuration error, not a cache miss
            if so != _SO_PATH or not _build():
                _lib_failed = True
                return None
        try:
            lib = ctypes.CDLL(so)
        except OSError:
            _lib_failed = True
            return None
        if not hasattr(lib, "rtpu_pipe_new"):
            # stale pre-pipe .so on disk (the Makefile target depends on
            # pipe.cc, so a rebuild picks it up): rebuild once and reload;
            # if the symbols are STILL missing, consumers fall back
            # per-feature via hasattr and native_status() reports stale.
            del lib
            if so == _SO_PATH and _build():
                try:
                    lib = ctypes.CDLL(so)
                except OSError:
                    _lib_failed = True
                    return None
            else:
                lib = ctypes.CDLL(so)
            _lib_stale = not hasattr(lib, "rtpu_pipe_new")
        lib.rtpu_store_open.restype = ctypes.c_void_p
        lib.rtpu_store_open.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.rtpu_store_close.argtypes = [ctypes.c_void_p]
        lib.rtpu_store_destroy.argtypes = [ctypes.c_char_p]
        lib.rtpu_create.restype = ctypes.c_uint64
        lib.rtpu_create.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                    ctypes.c_uint64]
        lib.rtpu_seal.restype = ctypes.c_int
        lib.rtpu_seal.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rtpu_get.restype = ctypes.c_uint64
        lib.rtpu_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                 ctypes.POINTER(ctypes.c_uint64)]
        lib.rtpu_contains.restype = ctypes.c_int
        lib.rtpu_contains.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rtpu_release.restype = ctypes.c_int
        lib.rtpu_release.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rtpu_delete.restype = ctypes.c_int
        lib.rtpu_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rtpu_evict.restype = ctypes.c_uint64
        lib.rtpu_evict.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.rtpu_stats.argtypes = [ctypes.c_void_p] + \
            [ctypes.POINTER(ctypes.c_uint64)] * 3
        if hasattr(lib, "rtpu_frag_stats"):  # absent in a pre-r11 .so
            lib.rtpu_frag_stats.argtypes = [ctypes.c_void_p] + \
                [ctypes.POINTER(ctypes.c_uint64)] * 3
        lib.rtpu_base.restype = ctypes.c_void_p
        lib.rtpu_base.argtypes = [ctypes.c_void_p]
        if hasattr(lib, "rtpu_pipe_new"):  # driver-engine symbols (r14)
            lib.rtpu_pipe_new.restype = ctypes.c_void_p
            lib.rtpu_pipe_new.argtypes = [ctypes.c_int, ctypes.c_uint64]
            lib.rtpu_pipe_send.restype = ctypes.c_int
            lib.rtpu_pipe_send.argtypes = [ctypes.c_void_p,
                                           ctypes.c_char_p, ctypes.c_uint64]
            lib.rtpu_pipe_drain.restype = ctypes.c_int64
            lib.rtpu_pipe_drain.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                            ctypes.c_uint64, ctypes.c_uint64]
            lib.rtpu_pipe_drain_pins.restype = ctypes.c_int64
            lib.rtpu_pipe_drain_pins.argtypes = [ctypes.c_void_p,
                                                 ctypes.c_void_p,
                                                 ctypes.c_uint64]
            lib.rtpu_pipe_stats.argtypes = [ctypes.c_void_p,
                                            ctypes.POINTER(ctypes.c_uint64)]
            lib.rtpu_pipe_shutdown.argtypes = [ctypes.c_void_p]
            lib.rtpu_pipe_close.argtypes = [ctypes.c_void_p]
            lib.rtpu_copy_mt.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                         ctypes.c_uint64, ctypes.c_int]
            lib.rtpu_lz4_bound.restype = ctypes.c_uint64
            lib.rtpu_lz4_bound.argtypes = [ctypes.c_uint64]
            lib.rtpu_lz4_compress.restype = ctypes.c_int64
            lib.rtpu_lz4_compress.argtypes = [ctypes.c_void_p,
                                              ctypes.c_uint64,
                                              ctypes.c_void_p,
                                              ctypes.c_uint64]
            lib.rtpu_lz4_decompress.restype = ctypes.c_int64
            lib.rtpu_lz4_decompress.argtypes = [ctypes.c_void_p,
                                                ctypes.c_uint64,
                                                ctypes.c_void_p,
                                                ctypes.c_uint64]
        _lib = lib
        return _lib


def native_status() -> dict:
    """Build/feature report for the tier-1 conftest contract: either the
    extension is fully loaded or the fallback is active — never a silent
    half-state (a .so that loads but lacks the pipe symbols after a
    rebuild attempt reports ``stale=True``)."""
    lib = load_store_lib()
    return {
        "loaded": lib is not None,
        "store": lib is not None,
        "pipe": lib is not None and hasattr(lib, "rtpu_pipe_new"),
        "lz4": lib is not None and hasattr(lib, "rtpu_lz4_compress"),
        "stale": _lib_stale,
        "so_path": _so_path(),
        "override": "RTPU_NATIVE_SO" in os.environ,
    }


def pipe_engine_available() -> bool:
    lib = load_store_lib()
    return lib is not None and hasattr(lib, "rtpu_pipe_new")


_pylib: Optional[ctypes.PyDLL] = None


def _load_pipe_pylib() -> Optional[ctypes.PyDLL]:
    """A PyDLL view of the same .so for the NON-blocking engine entry
    points (send/stats/pin-drain: mutex + memcpy + notify, microseconds).

    Calling those through the ordinary CDLL would release the GIL and
    then have to RE-ACQUIRE it on return — on a contended 2-vCPU box the
    reacquisition convoys behind whichever reader thread grabbed it,
    costing hundreds of µs per send (measured). Blocking entry points
    (drain, close) stay on the CDLL so they really do release the GIL.
    """
    global _pylib
    if _pylib is not None:
        return _pylib
    if not pipe_engine_available():
        return None
    with _lib_lock:
        if _pylib is not None:
            return _pylib
        try:
            plib = ctypes.PyDLL(_so_path())
        except OSError:
            return None
        plib.rtpu_pipe_send.restype = ctypes.c_int
        plib.rtpu_pipe_send.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                        ctypes.c_uint64]
        plib.rtpu_pipe_stats.argtypes = [ctypes.c_void_p,
                                         ctypes.POINTER(ctypes.c_uint64)]
        plib.rtpu_pipe_drain_pins.restype = ctypes.c_int64
        plib.rtpu_pipe_drain_pins.argtypes = [ctypes.c_void_p,
                                              ctypes.c_void_p,
                                              ctypes.c_uint64]
        _pylib = plib
        return _pylib


_ID_BYTES = 20  # kIdBytes in native/store.cc


def _pad_id(obj_id: bytes) -> bytes:
    """Normalize an id to exactly the native id width (the C side reads a
    fixed 20 bytes; shorter ids would make ctypes read past the buffer)."""
    return obj_id[:_ID_BYTES].ljust(_ID_BYTES, b"\x00")


class NativeArena:
    """Python handle over one native store arena."""

    def __init__(self, session: str, capacity: int = 1 << 30):
        lib = load_store_lib()
        if lib is None:
            raise RuntimeError("native store library unavailable")
        self._lib = lib
        self.name = f"/rtpu-arena-{session}".encode()
        self._store = lib.rtpu_store_open(self.name, capacity)
        if not self._store:
            raise RuntimeError("failed to open native arena")
        self._base = lib.rtpu_base(self._store)
        self._capacity = capacity
        # Monotonic populated high-water mark (arena offset): pages below
        # it have been committed by madvise or a first write, and nothing
        # ever decommits them (no MADV_REMOVE/hole-punch in the store),
        # so create() only needs to bulk-populate the part of an extent
        # above the mark. Process-local is fine — a stale-low mark only
        # costs a redundant (cheap) madvise walk.
        self._populated_end = 0
        self._libc_madvise = None
        try:
            libc = ctypes.CDLL(None, use_errno=True)
            libc.madvise.argtypes = [ctypes.c_void_p, ctypes.c_size_t,
                                     ctypes.c_int]
            self._libc_madvise = libc.madvise
        except Exception:
            pass
        # Workers skip: the arena is one shared mapping, so the driver's
        # (or daemon's) prefault covers every attacher — a per-worker
        # re-walk would only burn CPU.
        if os.environ.get("RTPU_WORKER") != "1":
            self.prefault_async()

    def prefault_async(self) -> None:
        """Fault the head of the arena's pages in a background thread.

        First-touch page faults dominate cold writes (~10x slower than a
        warm memcpy: 4k faults per 16 MiB object). MADV_POPULATE_WRITE
        allocates the tmpfs pages WITHOUT modifying contents, so it is
        safe to run concurrently with allocations; kernels without it
        (<5.14) just skip (first writes stay slower).

        Bounded by RTPU_STORE_PREFAULT_BYTES (default 256 MiB; "0"
        disables, "all" populates the whole arena): each populated page
        COMMITS physical tmpfs memory, so faulting the full capacity up
        front would turn the arena's lazy allocation into an eager
        capacity-sized commit the OOM killer sees at init.
        """
        import threading

        from ray_tpu import config

        setting = str(config.get("store_prefault_bytes"))
        if setting == "0":
            return
        limit = self._capacity if setting == "all" else min(
            int(setting), self._capacity)
        madvise = self._libc_madvise
        if madvise is None:
            return
        madv_populate_write = self._MADV_POPULATE_WRITE
        base = self._base

        def run():
            try:
                from ray_tpu.util import metric_defs

                progress = metric_defs.get(
                    "rtpu_object_store_prefault_bytes")
            except Exception:
                progress = None
            page = 4096
            start = (base + page - 1) // page * page
            end = base + limit
            chunk = 64 << 20
            off = start
            while off < end:
                n = min(chunk, end - off)
                if madvise(ctypes.c_void_p(off),
                           ctypes.c_size_t(n),
                           madv_populate_write) != 0:
                    return  # EINVAL on old kernels: give up quietly
                off += n
                # let create() skip the already-populated head (GIL makes
                # the plain store safe; a racing lower max() only costs a
                # redundant madvise walk)
                self._populated_end = max(self._populated_end,
                                          off - base)
                if progress is not None:
                    try:
                        progress.set(off - base)
                    except Exception:
                        progress = None

        threading.Thread(target=run, daemon=True,
                         name="rtpu-arena-prefault").start()

    _MADV_POPULATE_WRITE = 23  # linux 5.14+

    def create(self, obj_id: bytes, size: int) -> Optional[memoryview]:
        off = self._lib.rtpu_create(self._store, _pad_id(obj_id), size)
        if off == 0:
            return None
        self._populate(off, size)
        buf = (ctypes.c_char * size).from_address(self._base + off)
        return memoryview(buf).cast("B")

    def _populate(self, off: int, size: int) -> None:
        """Bulk-commit the extent's unfaulted pages before the caller's
        memcpy: one MADV_POPULATE_WRITE walk instead of a first-touch
        fault every 4 KiB during the copy.

        Fresh tmpfs pages must be zero-filled either way, so this only
        shaves the trap overhead (measured 181 -> 146 ms for a cold
        256 MiB extent on this box; warm extents skip via the watermark
        and write at memcpy speed, ~45 ms). The full win comes from
        extent REUSE — once the arena has been written once, every put
        runs warm."""
        end = off + size
        if self._libc_madvise is None or end <= self._populated_end:
            return
        page = 4096
        start = max(off, self._populated_end) // page * page
        aend = (end + page - 1) // page * page
        if self._libc_madvise(ctypes.c_void_p(self._base + start),
                              ctypes.c_size_t(aend - start),
                              self._MADV_POPULATE_WRITE) != 0:
            # EINVAL = kernel lacks MADV_POPULATE_WRITE (<5.14): disable
            # for good. Transient failures (ENOMEM under pressure) must
            # NOT disable the fast path — the next extent may succeed.
            if ctypes.get_errno() == errno.EINVAL:
                self._libc_madvise = None
            return
        self._populated_end = max(self._populated_end, end)

    def seal(self, obj_id: bytes) -> None:
        self._lib.rtpu_seal(self._store, _pad_id(obj_id))

    def get(self, obj_id: bytes) -> Optional[memoryview]:
        size = ctypes.c_uint64()
        off = self._lib.rtpu_get(self._store, _pad_id(obj_id), ctypes.byref(size))
        if off == 0:
            return None
        buf = (ctypes.c_char * size.value).from_address(self._base + off)
        # Readonly: sealed objects are immutable shared memory; a writable
        # view would let `get` callers silently corrupt every other reader
        # (the mmap fallback maps PROT_READ for the same reason).
        return memoryview(buf).cast("B").toreadonly()

    def contains(self, obj_id: bytes) -> bool:
        return bool(self._lib.rtpu_contains(self._store, _pad_id(obj_id)))

    def release(self, obj_id: bytes) -> None:
        self._lib.rtpu_release(self._store, _pad_id(obj_id))

    def delete(self, obj_id: bytes) -> None:
        self._lib.rtpu_delete(self._store, _pad_id(obj_id))

    def evict(self, nbytes: int) -> int:
        return int(self._lib.rtpu_evict(self._store, nbytes))

    def stats(self) -> dict:
        cap = ctypes.c_uint64()
        used = ctypes.c_uint64()
        num = ctypes.c_uint64()
        self._lib.rtpu_stats(self._store, ctypes.byref(cap),
                             ctypes.byref(used), ctypes.byref(num))
        return {"capacity": cap.value, "used": used.value,
                "num_objects": num.value}

    def frag_stats(self) -> dict:
        """Free-list occupancy/fragmentation: block count, total free
        bytes, and the largest contiguous free block (the biggest object
        the arena still fits without eviction)."""
        if not hasattr(self._lib, "rtpu_frag_stats"):
            return {}
        blocks = ctypes.c_uint64()
        free_b = ctypes.c_uint64()
        largest = ctypes.c_uint64()
        self._lib.rtpu_frag_stats(self._store, ctypes.byref(blocks),
                                  ctypes.byref(free_b),
                                  ctypes.byref(largest))
        return {"free_blocks": blocks.value, "free_bytes": free_b.value,
                "largest_free_bytes": largest.value}

    def close(self) -> None:
        if self._store:
            self._lib.rtpu_store_close(self._store)
            self._store = None

    @staticmethod
    def destroy(session: str) -> None:
        lib = load_store_lib()
        if lib is not None:
            lib.rtpu_store_destroy(f"/rtpu-arena-{session}".encode())


# ---------------------------------------------------------------------------
# GIL-free control-pipe engine (driver side of every worker connection)
# ---------------------------------------------------------------------------

#: drain-record types (native/pipe.cc append_record)
REC_MSG = 0        # one assembled pickle message
REC_REFPINS = 1    # packed net borrow transitions (id[16] + i8)*


class NativePipe:
    """One native sender/receiver pair over an existing connection fd.

    The engine OWNS all reads and writes on the fd from construction on —
    the Python ``Connection`` object must keep the fd alive but never
    touch it again. ``send`` enqueues pre-pickled bytes for the sender
    thread (framing + coalescing + the write syscall happen with the GIL
    released); ``drain`` blocks GIL-free and returns every fully-assembled
    record the receiver queued, so one GIL acquisition services a whole
    burst of worker messages.
    """

    def __init__(self, fd: int, coalesce_us: int = 0):
        lib = load_store_lib()
        if lib is None or not hasattr(lib, "rtpu_pipe_new"):
            raise RuntimeError("native pipe engine unavailable")
        self._lib = lib
        # GIL-holding view for the non-blocking entry points (see
        # _load_pipe_pylib); falls back to the CDLL if PyDLL load failed
        self._qlib = _load_pipe_pylib() or lib
        self._p = lib.rtpu_pipe_new(fd, coalesce_us)
        if not self._p:
            raise RuntimeError("failed to start native pipe engine")
        self._buf = ctypes.create_string_buffer(1 << 20)
        # lifetime guard: close() must not free the native struct while
        # another thread is inside a C call on it. _mu is held only for
        # nanoseconds (counter bumps) — never across a blocking call.
        self._mu = threading.Lock()
        self._inflight = 0

    def _enter(self):
        with self._mu:
            if self._p is None:
                return None
            self._inflight += 1
            return self._p

    def _exit(self) -> None:
        with self._mu:
            self._inflight -= 1

    def send(self, buf) -> bool:
        """Enqueue one pre-pickled message. False when the engine closed."""
        if not isinstance(buf, bytes):
            buf = bytes(buf)  # ForkingPickler.dumps returns a memoryview
        p = self._enter()
        if p is None:
            return False
        try:
            return self._qlib.rtpu_pipe_send(p, buf, len(buf)) == 0
        finally:
            self._exit()

    def drain(self, timeout: float = 0.5):
        """Every queued record, or [] on timeout, or None on EOF.

        Records are ``(rec_type, payload)`` pairs; payloads are bytes
        copies so the reusable drain buffer can be recycled immediately.
        """
        p = self._enter()
        if p is None:
            return None
        try:
            n = self._lib.rtpu_pipe_drain(p, self._buf, len(self._buf),
                                          int(timeout * 1000))
            if n == -1:
                return None
            if n < -1:
                # first record alone exceeds the buffer: grow and retry
                self._buf = ctypes.create_string_buffer(
                    max(-n, 2 * len(self._buf)))
                n = self._lib.rtpu_pipe_drain(p, self._buf, len(self._buf),
                                              int(timeout * 1000))
                if n == -1:
                    return None
                if n < 0:
                    return []
        finally:
            self._exit()
        out = []
        # string_at copies ONLY the drained bytes (the .raw property would
        # copy the whole reusable buffer on every drain)
        raw = ctypes.string_at(self._buf, n)
        off = 0
        while off < n:
            typ = raw[off]
            ln = int.from_bytes(raw[off + 1:off + 5], "little")
            out.append((typ, raw[off + 5:off + 5 + ln]))
            off += 5 + ln
        return out

    def drain_pins(self):
        """Serialize-and-clear the native borrow table (worker death):
        list of (oid16, count)."""
        p = self._enter()
        if p is None:
            return []
        try:
            cap = 64 << 10
            while True:
                buf = ctypes.create_string_buffer(cap)
                n = self._qlib.rtpu_pipe_drain_pins(p, buf, cap)
                if n >= 0:
                    break
                cap = -n
        finally:
            self._exit()
        out = []
        raw = ctypes.string_at(buf, n)
        off = 0
        while off < n:
            oid = raw[off:off + 16]
            count = int.from_bytes(raw[off + 16:off + 24], "little",
                                   signed=True)
            out.append((oid, count))
            off += 24
        return out

    def stats(self) -> dict:
        p = self._enter()
        if p is None:
            return {}
        try:
            arr = (ctypes.c_uint64 * 8)()
            self._qlib.rtpu_pipe_stats(p, arr)
        finally:
            self._exit()
        keys = ("sent_frames", "sent_msgs", "sent_bytes", "recv_frames",
                "recv_msgs", "recv_bytes", "refpin_deltas",
                "refpin_transitions")
        return dict(zip(keys, (int(v) for v in arr)))

    def shutdown(self) -> None:
        """Stop the engine without joining its threads (safe from the
        drain thread itself); ``close`` later reclaims them."""
        p = self._enter()
        if p is None:
            return
        try:
            self._lib.rtpu_pipe_shutdown(p)
        finally:
            self._exit()

    def close(self) -> None:
        """Shutdown + join + free. Blocked calls (a drain waiting on its
        timeout) are woken by shutdown's EOF flag, then the free waits
        for the in-flight count to reach zero."""
        import time as _time

        self.shutdown()  # wakes any blocked drain (EOF) and the sender
        with self._mu:
            p, self._p = self._p, None
        if p is None:
            return
        while True:
            with self._mu:
                if self._inflight == 0:
                    break
            _time.sleep(0.005)
        self._lib.rtpu_pipe_close(p)

    def __del__(self):  # pragma: no cover - GC-order dependent
        try:
            self.close()
        except Exception:
            pass


# ---------------------------------------------------------------------------
# data-plane primitives: multi-threaded memcpy + LZ4 spill codec
# ---------------------------------------------------------------------------

def _buf_addr(obj, writable: bool):
    """(address, length, keepalive) for a bytes-like object. numpy
    preserves the source's writability, so a readonly view through a
    writable buffer still exposes its address without a copy."""
    import numpy as np

    arr = np.frombuffer(obj, dtype=np.uint8)
    if writable and not arr.flags.writeable:
        raise ValueError("destination buffer is read-only")
    return arr.ctypes.data, arr.nbytes, arr


def parallel_copy(dst, src, threads: int = 0) -> int:
    """Multi-threaded memcpy dst <- src (GIL released for the duration).
    Returns bytes copied. Raises when the engine is unavailable — callers
    gate on ``pipe_engine_available()`` or catch and fall back."""
    lib = load_store_lib()
    if lib is None or not hasattr(lib, "rtpu_copy_mt"):
        raise RuntimeError("native copy unavailable")
    daddr, dlen, dref = _buf_addr(dst, writable=True)
    saddr, slen, sref = _buf_addr(src, writable=False)
    n = min(dlen, slen)
    lib.rtpu_copy_mt(daddr, saddr, n, threads)
    del dref, sref
    return n


def lz4_compress(src) -> "Optional[bytes]":
    """LZ4-block compress; None when the native codec is unavailable or
    the output would not fit the bound (incompressible guard)."""
    lib = load_store_lib()
    if lib is None or not hasattr(lib, "rtpu_lz4_compress"):
        return None
    saddr, slen, sref = _buf_addr(src, writable=False)
    cap = int(lib.rtpu_lz4_bound(slen))
    out = ctypes.create_string_buffer(cap)
    n = lib.rtpu_lz4_compress(saddr, slen, out, cap)
    del sref
    if n < 0:
        return None
    return out.raw[:n]


def lz4_decompress(src, raw_size: int) -> bytes:
    """Inverse of lz4_compress; raises ValueError on malformed input."""
    lib = load_store_lib()
    if lib is None or not hasattr(lib, "rtpu_lz4_decompress"):
        raise RuntimeError("native lz4 unavailable")
    saddr, slen, sref = _buf_addr(src, writable=False)
    out = ctypes.create_string_buffer(raw_size if raw_size else 1)
    n = lib.rtpu_lz4_decompress(saddr, slen, out, raw_size)
    del sref
    if n != raw_size:
        raise ValueError(f"lz4 decompress produced {n}, wanted {raw_size}")
    return out.raw[:raw_size]


def lz4_decompress_into(src, dst) -> int:
    """Decompress directly into a writable buffer (arena view / mmap) —
    the restore path must not materialize a second copy in the heap."""
    lib = load_store_lib()
    if lib is None or not hasattr(lib, "rtpu_lz4_decompress"):
        raise RuntimeError("native lz4 unavailable")
    saddr, slen, sref = _buf_addr(src, writable=False)
    daddr, dlen, dref = _buf_addr(dst, writable=True)
    n = lib.rtpu_lz4_decompress(saddr, slen, daddr, dlen)
    del sref, dref
    if n < 0:
        raise ValueError("malformed lz4 block")
    return int(n)
