"""ctypes bindings for the C++ native runtime components.

Role analog: the reference's Cython bridge (``python/ray/_raylet.pyx``) in
miniature — the native pieces are C++ (``native/``), and Python talks to
them through a flat C API (ctypes; pybind11 isn't in the image). The .so is
built on first use with g++ and cached; every consumer must handle
``load_store_lib() is None`` and fall back to the pure-Python path.
"""

from __future__ import annotations

import ctypes
import errno
import os
import subprocess
import threading
from typing import Optional

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "build", "librtpu_store.so")

_lib_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_lib_failed = False


def _build() -> bool:
    try:
        subprocess.run(
            ["make", "-C", _NATIVE_DIR, "-s"],
            check=True, capture_output=True, timeout=120)
        return os.path.exists(_SO_PATH)
    except Exception:
        return False


def load_store_lib() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native store library, or None."""
    global _lib, _lib_failed
    with _lib_lock:
        if _lib is not None:
            return _lib
        if _lib_failed:
            return None
        if not os.path.exists(_SO_PATH) and not _build():
            _lib_failed = True
            return None
        try:
            lib = ctypes.CDLL(_SO_PATH)
        except OSError:
            _lib_failed = True
            return None
        lib.rtpu_store_open.restype = ctypes.c_void_p
        lib.rtpu_store_open.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.rtpu_store_close.argtypes = [ctypes.c_void_p]
        lib.rtpu_store_destroy.argtypes = [ctypes.c_char_p]
        lib.rtpu_create.restype = ctypes.c_uint64
        lib.rtpu_create.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                    ctypes.c_uint64]
        lib.rtpu_seal.restype = ctypes.c_int
        lib.rtpu_seal.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rtpu_get.restype = ctypes.c_uint64
        lib.rtpu_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                 ctypes.POINTER(ctypes.c_uint64)]
        lib.rtpu_contains.restype = ctypes.c_int
        lib.rtpu_contains.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rtpu_release.restype = ctypes.c_int
        lib.rtpu_release.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rtpu_delete.restype = ctypes.c_int
        lib.rtpu_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rtpu_evict.restype = ctypes.c_uint64
        lib.rtpu_evict.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.rtpu_stats.argtypes = [ctypes.c_void_p] + \
            [ctypes.POINTER(ctypes.c_uint64)] * 3
        if hasattr(lib, "rtpu_frag_stats"):  # absent in a pre-r11 .so
            lib.rtpu_frag_stats.argtypes = [ctypes.c_void_p] + \
                [ctypes.POINTER(ctypes.c_uint64)] * 3
        lib.rtpu_base.restype = ctypes.c_void_p
        lib.rtpu_base.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


_ID_BYTES = 20  # kIdBytes in native/store.cc


def _pad_id(obj_id: bytes) -> bytes:
    """Normalize an id to exactly the native id width (the C side reads a
    fixed 20 bytes; shorter ids would make ctypes read past the buffer)."""
    return obj_id[:_ID_BYTES].ljust(_ID_BYTES, b"\x00")


class NativeArena:
    """Python handle over one native store arena."""

    def __init__(self, session: str, capacity: int = 1 << 30):
        lib = load_store_lib()
        if lib is None:
            raise RuntimeError("native store library unavailable")
        self._lib = lib
        self.name = f"/rtpu-arena-{session}".encode()
        self._store = lib.rtpu_store_open(self.name, capacity)
        if not self._store:
            raise RuntimeError("failed to open native arena")
        self._base = lib.rtpu_base(self._store)
        self._capacity = capacity
        # Monotonic populated high-water mark (arena offset): pages below
        # it have been committed by madvise or a first write, and nothing
        # ever decommits them (no MADV_REMOVE/hole-punch in the store),
        # so create() only needs to bulk-populate the part of an extent
        # above the mark. Process-local is fine — a stale-low mark only
        # costs a redundant (cheap) madvise walk.
        self._populated_end = 0
        self._libc_madvise = None
        try:
            libc = ctypes.CDLL(None, use_errno=True)
            libc.madvise.argtypes = [ctypes.c_void_p, ctypes.c_size_t,
                                     ctypes.c_int]
            self._libc_madvise = libc.madvise
        except Exception:
            pass
        # Workers skip: the arena is one shared mapping, so the driver's
        # (or daemon's) prefault covers every attacher — a per-worker
        # re-walk would only burn CPU.
        if os.environ.get("RTPU_WORKER") != "1":
            self.prefault_async()

    def prefault_async(self) -> None:
        """Fault the head of the arena's pages in a background thread.

        First-touch page faults dominate cold writes (~10x slower than a
        warm memcpy: 4k faults per 16 MiB object). MADV_POPULATE_WRITE
        allocates the tmpfs pages WITHOUT modifying contents, so it is
        safe to run concurrently with allocations; kernels without it
        (<5.14) just skip (first writes stay slower).

        Bounded by RTPU_STORE_PREFAULT_BYTES (default 256 MiB; "0"
        disables, "all" populates the whole arena): each populated page
        COMMITS physical tmpfs memory, so faulting the full capacity up
        front would turn the arena's lazy allocation into an eager
        capacity-sized commit the OOM killer sees at init.
        """
        import threading

        from ray_tpu import config

        setting = str(config.get("store_prefault_bytes"))
        if setting == "0":
            return
        limit = self._capacity if setting == "all" else min(
            int(setting), self._capacity)
        madvise = self._libc_madvise
        if madvise is None:
            return
        madv_populate_write = self._MADV_POPULATE_WRITE
        base = self._base

        def run():
            try:
                from ray_tpu.util import metric_defs

                progress = metric_defs.get(
                    "rtpu_object_store_prefault_bytes")
            except Exception:
                progress = None
            page = 4096
            start = (base + page - 1) // page * page
            end = base + limit
            chunk = 64 << 20
            off = start
            while off < end:
                n = min(chunk, end - off)
                if madvise(ctypes.c_void_p(off),
                           ctypes.c_size_t(n),
                           madv_populate_write) != 0:
                    return  # EINVAL on old kernels: give up quietly
                off += n
                # let create() skip the already-populated head (GIL makes
                # the plain store safe; a racing lower max() only costs a
                # redundant madvise walk)
                self._populated_end = max(self._populated_end,
                                          off - base)
                if progress is not None:
                    try:
                        progress.set(off - base)
                    except Exception:
                        progress = None

        threading.Thread(target=run, daemon=True,
                         name="rtpu-arena-prefault").start()

    _MADV_POPULATE_WRITE = 23  # linux 5.14+

    def create(self, obj_id: bytes, size: int) -> Optional[memoryview]:
        off = self._lib.rtpu_create(self._store, _pad_id(obj_id), size)
        if off == 0:
            return None
        self._populate(off, size)
        buf = (ctypes.c_char * size).from_address(self._base + off)
        return memoryview(buf).cast("B")

    def _populate(self, off: int, size: int) -> None:
        """Bulk-commit the extent's unfaulted pages before the caller's
        memcpy: one MADV_POPULATE_WRITE walk instead of a first-touch
        fault every 4 KiB during the copy.

        Fresh tmpfs pages must be zero-filled either way, so this only
        shaves the trap overhead (measured 181 -> 146 ms for a cold
        256 MiB extent on this box; warm extents skip via the watermark
        and write at memcpy speed, ~45 ms). The full win comes from
        extent REUSE — once the arena has been written once, every put
        runs warm."""
        end = off + size
        if self._libc_madvise is None or end <= self._populated_end:
            return
        page = 4096
        start = max(off, self._populated_end) // page * page
        aend = (end + page - 1) // page * page
        if self._libc_madvise(ctypes.c_void_p(self._base + start),
                              ctypes.c_size_t(aend - start),
                              self._MADV_POPULATE_WRITE) != 0:
            # EINVAL = kernel lacks MADV_POPULATE_WRITE (<5.14): disable
            # for good. Transient failures (ENOMEM under pressure) must
            # NOT disable the fast path — the next extent may succeed.
            if ctypes.get_errno() == errno.EINVAL:
                self._libc_madvise = None
            return
        self._populated_end = max(self._populated_end, end)

    def seal(self, obj_id: bytes) -> None:
        self._lib.rtpu_seal(self._store, _pad_id(obj_id))

    def get(self, obj_id: bytes) -> Optional[memoryview]:
        size = ctypes.c_uint64()
        off = self._lib.rtpu_get(self._store, _pad_id(obj_id), ctypes.byref(size))
        if off == 0:
            return None
        buf = (ctypes.c_char * size.value).from_address(self._base + off)
        # Readonly: sealed objects are immutable shared memory; a writable
        # view would let `get` callers silently corrupt every other reader
        # (the mmap fallback maps PROT_READ for the same reason).
        return memoryview(buf).cast("B").toreadonly()

    def contains(self, obj_id: bytes) -> bool:
        return bool(self._lib.rtpu_contains(self._store, _pad_id(obj_id)))

    def release(self, obj_id: bytes) -> None:
        self._lib.rtpu_release(self._store, _pad_id(obj_id))

    def delete(self, obj_id: bytes) -> None:
        self._lib.rtpu_delete(self._store, _pad_id(obj_id))

    def evict(self, nbytes: int) -> int:
        return int(self._lib.rtpu_evict(self._store, nbytes))

    def stats(self) -> dict:
        cap = ctypes.c_uint64()
        used = ctypes.c_uint64()
        num = ctypes.c_uint64()
        self._lib.rtpu_stats(self._store, ctypes.byref(cap),
                             ctypes.byref(used), ctypes.byref(num))
        return {"capacity": cap.value, "used": used.value,
                "num_objects": num.value}

    def frag_stats(self) -> dict:
        """Free-list occupancy/fragmentation: block count, total free
        bytes, and the largest contiguous free block (the biggest object
        the arena still fits without eviction)."""
        if not hasattr(self._lib, "rtpu_frag_stats"):
            return {}
        blocks = ctypes.c_uint64()
        free_b = ctypes.c_uint64()
        largest = ctypes.c_uint64()
        self._lib.rtpu_frag_stats(self._store, ctypes.byref(blocks),
                                  ctypes.byref(free_b),
                                  ctypes.byref(largest))
        return {"free_blocks": blocks.value, "free_bytes": free_b.value,
                "largest_free_bytes": largest.value}

    def close(self) -> None:
        if self._store:
            self._lib.rtpu_store_close(self._store)
            self._store = None

    @staticmethod
    def destroy(session: str) -> None:
        lib = load_store_lib()
        if lib is not None:
            lib.rtpu_store_destroy(f"/rtpu-arena-{session}".encode())
