"""``python -m ray_tpu.scripts`` — the CLI.

Role analog: ``python/ray/scripts/scripts.py`` (``ray status/list/
timeline/job ...``) adapted to the daemonless architecture: commands that
need a cluster boot one in-process (job submit), the rest inspect local
artifacts (shm sessions, timelines, experiment dirs) or run the bench.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _cmd_status(args) -> int:
    shm = [f for f in os.listdir("/dev/shm") if f.startswith("rtpu-")]
    arenas = [f for f in shm if f.startswith("rtpu-arena-")]
    print(f"shm arenas: {len(arenas)}")
    for a in arenas:
        size = os.stat(os.path.join("/dev/shm", a)).st_size
        print(f"  {a}  ({size >> 20} MiB mapped)")
    print(f"other rtpu shm segments: {len(shm) - len(arenas)}")
    if getattr(args, "url", None):
        # raised watchdog alerts from a running head (/api/alerts)
        try:
            alerts = _fetch_api(args.url, "/api/alerts") or []
        except Exception as e:
            print(f"alerts: unavailable ({e})")
            return 0
        if not alerts:
            print("alerts: none raised")
        for a in alerts:
            print(f"ALERT [{a.get('severity', '?'):7}] {a.get('alert')}: "
                  f"value={a.get('value'):.4g} "
                  f"threshold={a.get('threshold')} — "
                  f"{a.get('description', '')}")
    return 0


def _cmd_events(args) -> int:
    """``rtpu events --url http://head:8265`` — the lifecycle-event log
    (worker/actor/node deaths with postmortems, spills, serve reroutes,
    alerts), newest last. ``--name worker_death`` filters; death rows
    print their postmortem cause + first error line."""
    path = f"/api/events?limit={args.limit}"
    if args.name:
        path += f"&name={args.name}"
    evs = _fetch_api(args.url, path) or []
    import datetime

    for ev in evs:
        ts = datetime.datetime.fromtimestamp(
            ev.get("ts", 0)).strftime("%H:%M:%S")
        sev = ev.get("severity", "info")
        extras = {k: v for k, v in ev.items()
                  if k not in ("name", "ts", "severity", "postmortem")}
        kv = " ".join(f"{k}={v}" for k, v in sorted(extras.items()))
        print(f"{ts} [{sev:7}] {ev.get('name', '?'):22} {kv}")
        pm = ev.get("postmortem")
        if pm:
            print(f"    postmortem: cause={pm.get('cause', '?')}")
            for ln in (pm.get("error_lines") or [])[-3:]:
                print(f"      {ln}")
    print(f"-- {len(evs)} event(s)")
    return 0


def _cmd_devices(args) -> int:
    """``rtpu devices --url http://head:8265`` — the device plane:
    every process's compiled-program registry (compiles/retraces/cost),
    HBM watermarks, and live-buffer census, merged cluster-wide. The
    first thing to read when steps are slow: a climbing retrace count
    on one program is a recompile storm."""
    rep = _fetch_api(args.url, "/api/devices") or {}
    tot = rep.get("totals") or {}
    line = (f"{tot.get('processes', 0)} process(es), "
            f"{tot.get('programs', 0)} program row(s), "
            f"{tot.get('compiles', 0)} compile(s), "
            f"{tot.get('retraces', 0)} retrace(s)")
    hbm = tot.get("hbm")
    if hbm:
        line += (f", hbm {hbm.get('bytes_in_use', 0) / 2**30:.2f}"
                 f"/{hbm.get('bytes_limit', 0) / 2**30:.2f} GiB")
    print(line)
    rows = rep.get("programs") or []
    if not rows:
        print("(no compiled programs registered yet)")
        return 0
    print(f"{'program':<34} {'where':<24} {'compiles':>8} "
          f"{'retraces':>8} {'calls':>8} {'compile_s':>9} "
          f"{'gflop/step':>10}")
    for r in rows[:args.limit]:
        where = (f"{r.get('node_id', '?')}/"
                 f"{r.get('worker_id') or r.get('component', '?')}")
        cost = r.get("cost") or {}
        flops = cost.get("flops")
        gf = (f"{flops / max(1, int(r.get('steps', 1))) / 1e9:.2f}"
              if flops else "-")
        print(f"{r.get('program', '?'):<34} {where:<24} "
              f"{r.get('compiles', 0):>8} {r.get('retraces', 0):>8} "
              f"{r.get('calls', 0):>8} "
              f"{r.get('compile_s_total', 0.0):>9.2f} {gf:>10}")
    if args.census:
        for proc in rep.get("processes") or ():
            lb = proc.get("live_buffers")
            if not lb:
                continue
            where = (f"{proc.get('node_id', '?')}/"
                     f"{proc.get('worker_id') or proc.get('component')}"
                     f" pid={proc.get('pid', '?')}")
            print(f"-- live buffers @ {where}: {lb.get('buffers', 0)} "
                  f"({lb.get('bytes', 0) / 2**20:.1f} MiB)")
            for g in (lb.get("groups") or ())[:10]:
                shape = "x".join(str(d) for d in g.get("shape", ()))
                print(f"     {g['dtype']:<10} [{shape:<20}] "
                      f"x{g['count']:<5} {g['bytes'] / 2**20:>8.1f} MiB")
    return 0


def _cmd_logs(args) -> int:
    """``rtpu logs --task <id> --url http://head:8265`` — cluster-wide
    log federation: resolve a task/actor/worker/node id to its log
    file(s) wherever they live and print bounded tails (error lines
    first). Dead workers resolve through their death events; live
    processes whose log file was deleted are read via /proc fds."""
    target = {k: getattr(args, k) for k in ("task_id", "actor_id",
                                            "worker_id", "node_id")
              if getattr(args, k, None)}
    if not target:
        print("rtpu logs needs one of --task/--actor/--worker/--node")
        return 2
    from urllib.parse import urlencode

    rows = _fetch_api(args.url, "/api/logs?" + urlencode(target)) or []
    for r in rows:
        print(f"==== node {r.get('node_id', '?')} · {r.get('label')} "
              f"({r.get('bytes', 0)} bytes) ====")
        if args.errors_only:
            for ln in r.get("error_lines") or []:
                print(f"  {ln}")
        else:
            print(r.get("tail", ""), end="")
            if not (r.get("tail") or "").endswith("\n"):
                print()
    if not rows:
        print(f"no logs resolved for {target}")
        return 1
    return 0


def _cmd_job_submit(args) -> int:
    import ray_tpu
    from ray_tpu.job_submission import JobSubmissionClient

    ray_tpu.init(ignore_reinit_error=True)
    client = JobSubmissionClient()
    runtime_env = {}
    if args.working_dir:
        runtime_env["working_dir"] = args.working_dir
    import shlex

    job_id = client.submit_job(entrypoint=shlex.join(args.entrypoint),
                               runtime_env=runtime_env)
    print(f"submitted {job_id}")
    if args.no_wait:
        return 0
    status = client.wait_until_finished(job_id, timeout=args.timeout)
    print(client.get_job_logs(job_id), end="")
    print(f"job {job_id}: {status}")
    return 0 if status == "SUCCEEDED" else 1


def _cmd_job_list(args) -> int:
    import ray_tpu
    from ray_tpu.job_submission import JobSubmissionClient

    ray_tpu.init(ignore_reinit_error=True)
    for info in JobSubmissionClient().list_jobs():
        print(f"{info.job_id}  {info.status}  {info.entrypoint!r}")
    return 0


def _cmd_timeline(args) -> int:
    """Chrome-trace export. ``--perfetto`` writes the UNIFIED timeline
    (cluster-federated spans + flight-recorder task phases + lock-wait
    slices + train-step telemetry, one process row per node, one thread
    track per worker) — load it in ui.perfetto.dev. ``--url`` fetches the
    same document from a running head's ``/api/perfetto`` endpoint, so no
    in-process session is needed."""
    import ray_tpu

    perfetto = getattr(args, "perfetto", None)
    if perfetto:
        out = perfetto
        url = getattr(args, "url", None)
        if url:
            import urllib.request

            with urllib.request.urlopen(
                    url.rstrip("/") + "/api/perfetto", timeout=60) as resp:
                doc = json.loads(resp.read()).get("result", {})
            with open(out, "w") as f:
                json.dump(doc, f)
        else:
            if not ray_tpu.is_initialized():
                print("no active session; pass --url http://<head>:8265 "
                      "to export from a running head's dashboard")
                return 1
            from ray_tpu.util.state import export_perfetto

            doc = export_perfetto(out)
        n = len(doc.get("traceEvents", []))
        print(f"wrote {out} ({n} events) — open in ui.perfetto.dev")
        return 0
    if not ray_tpu.is_initialized():
        print("no active session in this process; timeline must be "
              "exported by the driver (ray_tpu.timeline(filename=...)) — "
              "or use --perfetto --url against a running head")
        return 1
    out = args.output or "timeline.json"
    ray_tpu.timeline(filename=out)
    print(f"wrote {out}")
    return 0


def _fetch_api(url: str, path: str):
    import urllib.request

    with urllib.request.urlopen(url.rstrip("/") + path,
                                timeout=120) as resp:
        return json.loads(resp.read()).get("result")


def _cmd_memory(args) -> int:
    """Object-memory forensics (reference ``ray memory`` role): every
    live object with size, owner, pin count + reasons, age, and the
    creating call-site when the profiler was armed. Three sources:
    --url fetches a running head's ``/api/memory``; --address dumps the
    cluster GCS object directory; otherwise the in-process driver's
    forensic view (requires an active session)."""
    rows = None
    report = None
    if getattr(args, "url", None):
        rows = _fetch_api(args.url, f"/api/memory?limit={args.limit}")
        try:
            report = _fetch_api(args.url, "/api/store")
        except Exception:
            report = None
    elif args.address:
        from ray_tpu.cluster.rpc import RpcClient

        cli = RpcClient(args.address, args.authkey.encode())
        try:
            rows = cli.call("obj_list", args.limit, timeout=30)
        finally:
            cli.close()
    else:
        import ray_tpu

        if not ray_tpu.is_initialized():
            print("no active session; pass --url http://<head>:8265 or "
                  "--address <gcs> --authkey <key>, or run inside a "
                  "driver")
            return 1
        from ray_tpu.util.state import memory_summary, store_report

        rows = memory_summary(limit=args.limit)
        report = store_report()
    total = sum(r["size"] or 0 for r in rows)
    print(f"{'OBJECT_ID':34} {'STATUS':8} {'SIZE':>12} {'PINS':>5} "
          f"{'AGE_S':>8} {'OWNER':16} REASONS")
    for r in sorted(rows, key=lambda r: -(r["size"] or 0)):
        reasons = ",".join(r.get("reasons") or ()) or "-"
        if r.get("call_site"):
            reasons += f"  @ {r['call_site']}"
        age = r.get("age_s")
        print(f"{r['object_id'][:32]:34} {r['status']:8} "
              f"{r['size'] or 0:>12} {r.get('pins', '-'):>5} "
              f"{age if age is not None else '-':>8} "
              f"{str(r.get('owner', '-'))[:16]:16} {reasons}")
    print(f"-- {len(rows)} objects, {total / 1e6:.1f} MB total")
    if report:
        frag = (f", fragmentation {report['fragmentation_pct']}% "
                f"(largest free {report.get('largest_free_bytes', 0) >> 20}"
                f" MiB over {report.get('free_blocks', '?')} blocks)"
                if "fragmentation_pct" in report else "")
        print(f"store[{report['backend']}]: "
              f"{report.get('arena_used_bytes', 0) >> 20} MiB in arena, "
              f"{report['file_segment_bytes'] >> 20} MiB file segments, "
              f"{report['spill_dir_bytes'] >> 20} MiB spilled{frag}")
    return 0


def _cmd_profile(args) -> int:
    """Cluster-wide CPU profile (the profiling plane): sample for
    --seconds (arming temporarily if needed) and write speedscope JSON /
    collapsed stacks, or print the merged top-self summary. --url runs
    against a running head's ``/api/profile`` — no in-process session
    needed."""
    fmt = ("speedscope" if (args.output or "").endswith(".json")
           else args.fmt)
    if args.url:
        q = f"/api/profile?fmt={fmt}"
        if args.seconds is not None:
            q += f"&seconds={args.seconds}"
        doc = _fetch_api(args.url, q)
    else:
        import ray_tpu

        if not ray_tpu.is_initialized():
            print("no active session; pass --url http://<head>:8265 to "
                  "profile a running head")
            return 1
        from ray_tpu.util import state

        if fmt == "speedscope":
            doc = state.export_speedscope(seconds=args.seconds)
        elif fmt == "collapsed":
            doc = state.profile_collapsed(seconds=args.seconds)
        else:
            doc = state.profile(seconds=args.seconds)
    if args.output:
        with open(args.output, "w") as f:
            if isinstance(doc, str):
                f.write(doc)
            else:
                json.dump(doc, f)
        print(f"wrote {args.output} — open at https://speedscope.app"
              if fmt == "speedscope" else f"wrote {args.output}")
        return 0
    if isinstance(doc, str):
        print(doc)
    elif fmt == "summary":
        print(f"{doc['total_samples']} samples "
              f"({doc['idle_samples']} idle) across "
              f"{len(doc['processes'])} processes")
        for comp, top in sorted(
                (doc.get("top_self_by_component") or {}).items()):
            print(f"[{comp}] top self-time:")
            for row in top[:10]:
                print(f"  {row['self_pct']:5.1f}%  "
                      f"{row['self_samples']:>6}  {row['function']}")
    else:
        print(json.dumps(doc, indent=1))
    return 0


def _cmd_bench(args) -> int:
    if getattr(args, "watch", False):
        from ray_tpu.util import tpu_watch

        # only forward an explicit --interval; otherwise tpu_watch.main
        # resolves the watch_interval knob (RTPU_WATCH_INTERVAL) itself
        argv = ([] if args.interval is None
                else ["--interval", str(args.interval)])
        return tpu_watch.main(argv)
    import runpy

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.argv = ["bench.py"]
    runpy.run_path(os.path.join(repo, "bench.py"), run_name="__main__")
    return 0


def _cmd_serve(args) -> int:
    """`ray_tpu serve run/deploy/status/shutdown` (reference serve CLI,
    ``python/ray/serve/scripts.py`` role). `run` hosts in-process; the
    others talk REST to a running instance's dashboard."""
    import json as _json
    import urllib.request

    def rest(method: str, url: str, payload=None):
        data = _json.dumps(payload).encode() if payload is not None else None
        req = urllib.request.Request(
            url + "/api/serve/applications", data=data, method=method,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as resp:
            return _json.loads(resp.read())

    if args.serve_cmd == "run":
        import ray_tpu
        from ray_tpu import serve
        from ray_tpu.dashboard import start_dashboard
        from ray_tpu.serve.config_api import (deploy_config, import_attr,
                                              load_config)

        ray_tpu.init(ignore_reinit_error=True)
        start_dashboard()
        if args.target.endswith((".yaml", ".yml", ".json")):
            names = deploy_config(load_config(args.target))
        else:
            app = import_attr(args.target)
            serve.run(app)
            names = ["default"]
        proxy = serve.start_http_proxy(port=args.http_port)
        print(f"serving {names} on http://127.0.0.1:{proxy.port} "
              f"(Ctrl-C to stop)", flush=True)
        try:
            import time as _time

            while True:
                _time.sleep(1)
        except KeyboardInterrupt:
            serve.shutdown()
            ray_tpu.shutdown()
        return 0
    if args.serve_cmd == "deploy":
        from ray_tpu.serve.config_api import load_config

        print(_json.dumps(rest("PUT", args.dashboard_url,
                               load_config(args.config)), indent=1))
        return 0
    if args.serve_cmd == "status":
        print(_json.dumps(rest("GET", args.dashboard_url), indent=1))
        return 0
    if args.serve_cmd == "shutdown":
        print(_json.dumps(rest("DELETE", args.dashboard_url), indent=1))
        return 0
    return 1


def _cmd_stack(args) -> int:
    """Dump python stacks of every live ray_tpu process (reference
    ``ray stack``, scripts.py:1830 — the py-spy role). With --url, a
    LIVE cluster-wide dump through the profiling plane: the head walks
    its own threads, pulls every worker over the control pipes, and
    fans a GCS pubsub stack request to every daemon (and ITS workers).
    Without, the local fallback: SIGUSR1+faulthandler into the session
    logs (works with no dashboard, even on wedged drivers)."""
    import signal
    import time

    if getattr(args, "url", None):
        dump = _fetch_api(args.url, "/api/stack")
        for node, procs in sorted((dump or {}).items()):
            for proc, threads in sorted(procs.items()):
                print(f"\n==== node {node} · {proc} ====")
                for tname, stack in sorted(threads.items()):
                    print(f"-- {tname}")
                    for frame in stack.split(";"):
                        print(f"   {frame}")
        return 0

    signaled = []
    for pid_dir in os.listdir("/proc"):
        if not pid_dir.isdigit():
            continue
        pid = int(pid_dir)
        try:
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                cmdline = f.read().replace(b"\0", b" ").decode("utf-8",
                                                               "replace")
        except OSError:
            continue
        # zygote-forked workers inherit the fork-server's cmdline
        # (ray_tpu.core.zygote); the zygote parent itself ignores SIGUSR1,
        # so signaling every match is safe and reaches all workers
        if ("ray_tpu.core.worker" in cmdline
                or "ray_tpu.core.zygote" in cmdline):
            try:
                os.kill(pid, signal.SIGUSR1)
                signaled.append(pid)
            except OSError:
                pass
    if not signaled:
        print("no live ray_tpu workers found")
        return 0
    time.sleep(0.5)  # let faulthandler write
    print(f"signaled {len(signaled)} workers: {signaled}")
    import glob

    shown = 0
    for log in sorted(glob.glob("/tmp/rtpu-*/logs/worker-*.log"),
                      key=os.path.getmtime, reverse=True):
        try:
            with open(log, errors="replace") as f:
                content = f.read()
        except OSError:
            continue
        if "Current thread" not in content:
            continue
        idx = content.rindex("Current thread")
        window = content[max(0, idx - 2000):idx + 4000]
        print(f"\n==== {log} ====")
        print(window)
        shown += 1
        if shown >= args.limit:
            break
    return 0


def _cmd_list_models(args) -> int:
    """``rtpu list models --url http://head:8265`` — per-replica model
    residency (tier, swap counters, inflight) + prefix-digest summaries,
    from the serve controller's load reports via ``/api/models``."""
    doc = _fetch_api(args.url, "/api/models") or {}
    deployments = doc.get("deployments") or {}
    if doc.get("error"):
        print(f"error: {doc['error']}")
    n_models = 0
    for dep, rec in sorted(deployments.items()):
        print(f"deployment {dep}:")
        for rid, rep in sorted((rec.get("replicas") or {}).items()):
            print(f"  replica {rid[:16]} inflight={rep.get('inflight', 0)}")
            for mid, m in sorted((rep.get("models") or {}).items()):
                n_models += 1
                extra = ""
                if "swaps_in" in m:
                    extra = (f" swaps={m.get('swaps_in', 0)}/"
                             f"{m.get('swaps_out', 0)}")
                print(f"    {mid:<24} {str(m.get('state', '-')):<8} "
                      f"inflight={m.get('inflight', 0)}{extra}")
            digest = rep.get("prefix_digest") or []
            if digest:
                tops = ", ".join(f"{d[0][:12]}:{d[1]}" for d in digest[:4])
                print(f"    prefix-digest: {tops}")
    print(f"-- {n_models} model(s) across {len(deployments)} "
          "multiplexed deployment(s)")
    return 0


def _cmd_list(args) -> int:
    """``rtpu list actors|pgs|models`` — dump the cluster GCS actor /
    placement-group directories (reference ``ray list actors`` role;
    these are the CLI senders for the ``actor_list`` / ``pg_list``
    RPCs the graftlint protocol family tracks), or the serve plane's
    model-residency report (``models``, dashboard-backed)."""
    from ray_tpu.cluster.rpc import RpcClient

    if args.what == "models":
        if not args.url:
            print("rtpu list models needs --url http://<head>:8265")
            return 2
        return _cmd_list_models(args)
    if not args.address:
        print(f"rtpu list {args.what} needs --address <gcs host:port>")
        return 2

    def _hex(v, n=32):
        return v.hex()[:n] if isinstance(v, bytes) else str(v or "-")[:n]

    cli = RpcClient(args.address, args.authkey.encode())
    try:
        if args.what == "actors":
            recs = cli.call("actor_list", timeout=30) or {}
            print(f"{'ACTOR_ID':34} {'STATE':10} {'NODE':18} NAME/CLASS")
            for aid, rec in sorted(recs.items(), key=lambda kv: _hex(kv[0])):
                label = (rec.get("name") or rec.get("class_name")
                         or rec.get("cls") or "-")
                print(f"{_hex(aid):34} {str(rec.get('state', '-')):10} "
                      f"{_hex(rec.get('node_id'), 16):18} {label}")
            print(f"-- {len(recs)} actor(s)")
        else:
            recs = cli.call("pg_list", timeout=30) or {}
            print(f"{'PG_ID':34} {'STRATEGY':12} {'BUNDLES':>7} ASSIGNED")
            for pid, rec in sorted(recs.items(), key=lambda kv: _hex(kv[0])):
                assignments = rec.get("assignments") or []
                assigned = sum(1 for a in assignments if a)
                print(f"{_hex(pid):34} {str(rec.get('strategy', '-')):12} "
                      f"{len(rec.get('bundles') or []):>7} "
                      f"{assigned}/{len(assignments)}")
            print(f"-- {len(recs)} placement group(s)")
    finally:
        cli.close()
    return 0


def _cmd_clean(args) -> int:
    import glob

    removed = 0
    for path in glob.glob("/dev/shm/rtpu-*"):
        try:
            os.unlink(path)
            removed += 1
        except OSError:
            pass
    print(f"removed {removed} shm segments")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="ray_tpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    stat = sub.add_parser("status", help="show local shm sessions/arenas "
                                         "(+ raised alerts with --url)")
    stat.add_argument("--url", default=None,
                      help="also show the watchdog's raised alerts from "
                           "a running head (http://host:8265)")

    ev = sub.add_parser("events", help="lifecycle-event log (deaths w/ "
                                       "postmortems, spills, alerts)")
    ev.add_argument("--url", default="http://127.0.0.1:8265",
                    help="running head's dashboard (http://host:8265)")
    ev.add_argument("--limit", type=int, default=200)
    ev.add_argument("--name", default=None,
                    help="only this event name (e.g. worker_death)")

    dv = sub.add_parser("devices", help="device plane: compiled-program "
                                        "registry + HBM census, "
                                        "cluster-wide")
    dv.add_argument("--url", default="http://127.0.0.1:8265",
                    help="running head's dashboard (http://host:8265)")
    dv.add_argument("--limit", type=int, default=50,
                    help="max program rows printed")
    dv.add_argument("--census", action="store_true",
                    help="also print each process's live-buffer census "
                         "grouped by shape/dtype")

    lg = sub.add_parser("logs", help="cluster-wide log fetch by task/"
                                     "actor/worker/node id")
    lg.add_argument("--url", default="http://127.0.0.1:8265",
                    help="running head's dashboard (http://host:8265)")
    lg.add_argument("--task", dest="task_id", default=None)
    lg.add_argument("--actor", dest="actor_id", default=None)
    lg.add_argument("--worker", dest="worker_id", default=None)
    lg.add_argument("--node", dest="node_id", default=None)
    lg.add_argument("--errors-only", action="store_true",
                    help="print only the extracted error lines")
    sub.add_parser("config", help="print every runtime knob (name, env "
                                  "var, default, current value)")
    sub.add_parser("clean", help="remove leftover rtpu shm segments")
    bench = sub.add_parser("bench", help="run the flagship benchmark")
    bench.add_argument("--watch", action="store_true",
                       help="daemon mode: probe the TPU tunnel all round; "
                            "on first success run the on-chip bench + "
                            "Pallas numerics check and cache the result")
    bench.add_argument("--interval", type=float, default=None)

    tl = sub.add_parser("timeline", help="export chrome trace")
    tl.add_argument("--output", "-o", default=None)
    tl.add_argument("--perfetto", metavar="OUT.json", default=None,
                    help="write the unified cluster timeline (spans + "
                         "task phases + lock waits + train steps) for "
                         "ui.perfetto.dev")
    tl.add_argument("--url", default=None,
                    help="with --perfetto: fetch from a running head's "
                         "dashboard (http://host:8265) instead of an "
                         "in-process session")

    mem = sub.add_parser("memory", help="object-memory forensics "
                                        "(reference `ray memory` role)")
    mem.add_argument("--address", default=None,
                     help="GCS address host:port (cluster mode)")
    mem.add_argument("--authkey", default="",
                     help="cluster authkey (with --address)")
    mem.add_argument("--url", default=None,
                     help="fetch from a running head's dashboard "
                          "(http://host:8265) instead of in-process")
    mem.add_argument("--limit", type=int, default=10000)

    ls = sub.add_parser("list", help="list cluster actors / placement "
                                     "groups / served models")
    ls.add_argument("what", choices=["actors", "pgs", "models"])
    ls.add_argument("--address", default=None,
                    help="GCS address host:port (actors/pgs)")
    ls.add_argument("--authkey", default="", help="cluster authkey")
    ls.add_argument("--url", default=None,
                    help="dashboard URL http://host:8265 (models)")

    st = sub.add_parser("stack", help="dump python stacks of live "
                                      "ray_tpu processes (py-spy role)")
    st.add_argument("--limit", type=int, default=16)
    st.add_argument("--url", default=None,
                    help="live cluster-wide dump via a running head's "
                         "dashboard (http://host:8265); default: local "
                         "SIGUSR1 into session logs")

    prof = sub.add_parser("profile",
                          help="cluster-wide sampling profile "
                               "(flamegraph/speedscope export)")
    prof.add_argument("--seconds", type=float, default=2.0,
                      help="sampling window; arms the profiler "
                           "temporarily when not already armed")
    prof.add_argument("--output", "-o", default=None,
                      help="write here (.json => speedscope)")
    prof.add_argument("--fmt", default="summary",
                      choices=["summary", "speedscope", "collapsed"])
    prof.add_argument("--url", default=None,
                      help="profile a running head via its dashboard "
                           "(http://host:8265)")

    up = sub.add_parser("up", help="launch a cluster from a yaml "
                                   "(reference `ray up` role)")
    up.add_argument("config")
    down = sub.add_parser("down", help="tear a cluster down")
    down.add_argument("config")

    srv = sub.add_parser("serve", help="serve deploy/run/status/shutdown "
                                       "(reference `serve` CLI role)")
    srvsub = srv.add_subparsers(dest="serve_cmd", required=True)
    sr = srvsub.add_parser("run", help="deploy a config or app and block")
    sr.add_argument("target", help="config.yaml OR module:app import path")
    sr.add_argument("--http-port", type=int, default=8000)
    sd = srvsub.add_parser("deploy",
                           help="PUT a config to a running instance's "
                                "dashboard REST endpoint")
    sd.add_argument("config")
    sd.add_argument("--dashboard-url", default="http://127.0.0.1:8265")
    ss = srvsub.add_parser("status")
    ss.add_argument("--dashboard-url", default="http://127.0.0.1:8265")
    sx = srvsub.add_parser("shutdown")
    sx.add_argument("--dashboard-url", default="http://127.0.0.1:8265")

    job = sub.add_parser("job", help="job submission")
    jobsub = job.add_subparsers(dest="job_cmd", required=True)
    js = jobsub.add_parser("submit")
    js.add_argument("--working-dir", default=None)
    js.add_argument("--no-wait", action="store_true")
    js.add_argument("--timeout", type=float, default=600.0)
    js.add_argument("entrypoint", nargs=argparse.REMAINDER)
    jobsub.add_parser("list")

    args = p.parse_args(argv)
    if args.cmd == "status":
        return _cmd_status(args)
    if args.cmd == "config":
        from ray_tpu import config as _config

        rows = _config.describe()
        w = max(len(r["env"]) for r in rows)
        for r in rows:
            mark = " *" if r["overridden"] else "  "
            print(f"{r['env']:<{w}}{mark} {r['current']!r:>14}  "
                  f"(default {r['default']!r}) — {r['doc']}")
        print("\n(* = overridden via environment)")
        return 0
    if args.cmd == "clean":
        return _cmd_clean(args)
    if args.cmd == "bench":
        return _cmd_bench(args)
    if args.cmd == "timeline":
        return _cmd_timeline(args)
    if args.cmd == "memory":
        return _cmd_memory(args)
    if args.cmd == "list":
        return _cmd_list(args)
    if args.cmd == "stack":
        return _cmd_stack(args)
    if args.cmd == "events":
        return _cmd_events(args)
    if args.cmd == "devices":
        return _cmd_devices(args)
    if args.cmd == "logs":
        return _cmd_logs(args)
    if args.cmd == "profile":
        return _cmd_profile(args)
    if args.cmd == "up":
        from ray_tpu.autoscaler import launcher

        out = launcher.up(launcher.load_config(args.config))
        print(f"head {'created' if out['head_created'] else 'alive'}: "
              f"{out['head'].node_id} @ {out['address']}; "
              f"{len(out['workers_started'])} worker host(s) started")
        return 0
    if args.cmd == "down":
        from ray_tpu.autoscaler import launcher

        n = launcher.down(launcher.load_config(args.config))
        print(f"terminated {n} node(s)/slice(s)")
        return 0
    if args.cmd == "serve":
        return _cmd_serve(args)
    if args.cmd == "job":
        if args.job_cmd == "submit":
            return _cmd_job_submit(args)
        if args.job_cmd == "list":
            return _cmd_job_list(args)
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
