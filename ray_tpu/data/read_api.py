"""Datasource read/from_* APIs.

Role analog: ``python/ray/data/read_api.py`` + ``data/datasource/``. Reads
are lazy in the reference via read tasks; here the file listing happens
eagerly (cheap) and per-file parsing runs as map tasks in the streaming
plan, which preserves the "read is parallelized over files" property.
"""

from __future__ import annotations

import builtins
import glob
import os
from typing import Any, Dict, Iterable, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.data.block import Block, block_from_rows
from ray_tpu.data.dataset import Dataset
from ray_tpu.data.execution import MapOp


def _paths(path_or_paths, suffix: str) -> List[str]:
    paths = ([path_or_paths] if isinstance(path_or_paths, str)
             else list(path_or_paths))
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(glob.glob(os.path.join(p, f"*{suffix}"))))
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(glob.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no files matched {path_or_paths!r}")
    return out


def _file_dataset(files: List[str], parse) -> Dataset:
    """One source block of file paths; parsing fans out as map tasks."""
    path_blocks = [{"__path": np.asarray([f], dtype=object)} for f in files]

    def _parse(block: Block) -> List[Block]:
        return [parse(str(block["__path"][0]))]

    refs = [ray_tpu.put(b) for b in path_blocks]
    return Dataset(refs, [MapOp(name="read", fn=_parse)])


# -- in-memory sources ------------------------------------------------------

def range(n: int, *, parallelism: int = 8,  # noqa: A001
          lazy: bool = False) -> Dataset:
    """Integer range dataset. ``lazy=True`` defers block creation to
    execution time: blocks are generated + put as the plan pulls them and
    the streaming exchange frees each one once consumed, so a range far
    larger than the object store can flow through a sort/shuffle without
    ever being materialized at once (map-only plans still retain their
    output blocks — only exchanges reclaim eagerly)."""
    parallelism = max(1, min(parallelism, n or 1))
    size = (n + parallelism - 1) // parallelism

    def gen():
        if not n:
            yield {}
            return
        for i in builtins.range(0, n, size):
            yield {"id": np.arange(i, min(i + size, n), dtype=np.int64)}

    if lazy:
        return Dataset(gen)
    return Dataset([ray_tpu.put(b) for b in gen()])


def range_tensor(n: int, *, shape=(1,), parallelism: int = 8) -> Dataset:
    parallelism = max(1, min(parallelism, n or 1))
    size = (n + parallelism - 1) // parallelism
    blocks = []
    for i in builtins.range(0, n, size):
        ids = np.arange(i, min(i + size, n), dtype=np.int64)
        data = np.broadcast_to(ids.reshape((-1,) + (1,) * len(shape)),
                               (len(ids),) + tuple(shape)).copy()
        blocks.append({"data": data})
    return Dataset([ray_tpu.put(b) for b in (blocks or [{}])])


def from_items(items: List[Any], *, parallelism: int = 8,
               lazy: bool = False) -> Dataset:
    n = len(items)
    parallelism = max(1, min(parallelism, n or 1))
    size = (n + parallelism - 1) // parallelism

    def gen():
        blocks = [block_from_rows(items[i:i + size])
                  for i in builtins.range(0, n, size)]
        yield from (blocks or [{}])

    if lazy:
        return Dataset(gen)
    return Dataset([ray_tpu.put(b) for b in gen()])


def from_numpy(arr: np.ndarray, *, column: str = "data",
               parallelism: int = 8) -> Dataset:
    n = len(arr)
    parallelism = max(1, min(parallelism, n or 1))
    size = (n + parallelism - 1) // parallelism
    blocks = [{column: arr[i:i + size]}
              for i in builtins.range(0, n, size)]
    return Dataset([ray_tpu.put(b) for b in (blocks or [{}])])


def from_pandas(df) -> Dataset:
    from ray_tpu.data.block import block_from_pandas

    return Dataset([ray_tpu.put(block_from_pandas(df))])


def from_arrow(table) -> Dataset:
    from ray_tpu.data.block import batch_to_block

    return Dataset([ray_tpu.put(batch_to_block(table))])


# -- file sources -----------------------------------------------------------

def read_parquet(path, **kw) -> Dataset:
    def parse(f: str) -> Block:
        import pyarrow.parquet as pq

        from ray_tpu.data.block import batch_to_block

        return batch_to_block(pq.read_table(f))

    return _file_dataset(_paths(path, ".parquet"), parse)


def read_csv(path, **kw) -> Dataset:
    def parse(f: str) -> Block:
        import pandas as pd

        from ray_tpu.data.block import block_from_pandas

        return block_from_pandas(pd.read_csv(f))

    return _file_dataset(_paths(path, ".csv"), parse)


def read_json(path, **kw) -> Dataset:
    def parse(f: str) -> Block:
        import pandas as pd

        from ray_tpu.data.block import block_from_pandas

        return block_from_pandas(pd.read_json(f, orient="records", lines=True))

    return _file_dataset(_paths(path, ".json"), parse)


def read_numpy(path, **kw) -> Dataset:
    def parse(f: str) -> Block:
        return {"data": np.load(f)}

    return _file_dataset(_paths(path, ".npy"), parse)


def read_images(path, *, size=None, mode: str = "RGB", **kw) -> Dataset:
    """Image files -> tensor column (reference ``data/datasource``
    image reader role). ``size=(H, W)`` resizes so blocks stack into one
    [N, H, W, C] array (the TPU-ingest-friendly layout); without it each
    image keeps its own shape in an object column."""
    def parse(f: str) -> Block:
        from PIL import Image

        img = Image.open(f).convert(mode)
        if size is not None:
            img = img.resize((size[1], size[0]))
        arr = np.asarray(img)
        if size is not None:
            return {"image": arr[None], "path": np.asarray([f], object)}
        boxed = np.empty(1, object)
        boxed[0] = arr
        return {"image": boxed, "path": np.asarray([f], object)}

    exts = (".png", ".jpg", ".jpeg", ".bmp", ".gif")
    paths = [p for p in _paths(path, "") if p.lower().endswith(exts)]
    if not paths:
        raise FileNotFoundError(f"no image files under {path!r}")
    return _file_dataset(paths, parse)


def read_text(path, **kw) -> Dataset:
    def parse(f: str) -> Block:
        with open(f) as fh:
            lines = [ln.rstrip("\n") for ln in fh]
        return {"text": np.asarray(lines, dtype=object)}

    return _file_dataset(_paths(path, ""), parse)


def read_binary_files(path, **kw) -> Dataset:
    def parse(f: str) -> Block:
        with open(f, "rb") as fh:
            data = fh.read()
        return {"bytes": np.asarray([data], dtype=object),
                "path": np.asarray([f], dtype=object)}

    return _file_dataset(_paths(path, ""), parse)
