"""Rule-based logical-plan optimizer for Data pipelines.

Role analog: the reference optimizer framework under
``python/ray/data/_internal/logical/`` — ``Rule``/``Optimizer`` interfaces
(``interfaces/optimizer.py``) with rules like
``rules/operator_fusion.py``. Each rule is a pure
``List[LogicalOp] -> List[LogicalOp]`` rewrite; the optimizer applies the
rule list to a fixpoint (bounded), so rules compose — e.g. eliminating a
redundant shuffle can expose two maps to the fusion rule.

Built-in rules:

- :class:`EliminateRedundantShuffles` — SAME-KIND back-to-back exchanges
  keep only the last: random_shuffle followed by an UNSEEDED
  random_shuffle, or repartition followed by repartition. Mixed kinds
  never collapse (a repartition is order-preserving and cannot stand in
  for a shuffle; block counts differ the other way);
- :class:`CollapseRepartitionIntoShuffle` — repartition followed by an
  UNSEEDED random_shuffle becomes one shuffle carrying the repartition's
  block count (the shuffle redistributes every row anyway);
- :class:`FuseLimits` — consecutive limits collapse to the minimum;
- :class:`OperatorFusionRule` — consecutive task-compute MapOps fuse into
  one stage (``fuse_ops``).

``ExecutionOptions.optimizer`` overrides the default; tests pin
golden plans against rule output (reference golden-plan optimizer tests).
"""

from __future__ import annotations

from typing import List

from ray_tpu.data.execution import (LimitOp, LogicalOp, MapOp, ShuffleOp,
                                    fuse_ops)


class Rule:
    """A pure logical-plan rewrite (reference ``Rule`` interface role)."""

    def apply(self, plan: List[LogicalOp]) -> List[LogicalOp]:
        raise NotImplementedError

    @property
    def name(self) -> str:
        return type(self).__name__


class OperatorFusionRule(Rule):
    def apply(self, plan: List[LogicalOp]) -> List[LogicalOp]:
        return fuse_ops(plan)


class EliminateRedundantShuffles(Rule):
    """Drop a full-data exchange whose effect the NEXT op reproduces:

    - ``random_shuffle`` followed by an UNSEEDED ``random_shuffle`` — the
      output distribution is identical either way;
    - ``repartition`` followed by ``repartition`` — the row set is
      unchanged and the last call decides the block count.

    Deliberately NOT collapsed: mixed kinds (a repartition is
    order-preserving, so it cannot stand in for a shuffle and vice versa
    — block counts differ), and any case where the surviving shuffle is
    SEEDED (dropping the predecessor would change the deterministic
    output the seed promises)."""

    def apply(self, plan: List[LogicalOp]) -> List[LogicalOp]:
        out: List[LogicalOp] = []
        for op in plan:
            prev = out[-1] if out else None
            if (isinstance(op, ShuffleOp) and isinstance(prev, ShuffleOp)
                    and ((op.kind == "random_shuffle"
                          and prev.kind == "random_shuffle"
                          and op.args.get("seed") is None)
                         or (op.kind == "repartition"
                             and prev.kind == "repartition"))):
                out[-1] = op  # later exchange wins
            else:
                out.append(op)
        return out


class CollapseRepartitionIntoShuffle(Rule):
    """``repartition(n)`` immediately followed by an UNSEEDED
    ``random_shuffle`` collapses to ``random_shuffle(num_blocks=n)``: the
    shuffle redistributes every row anyway, so the order-preserving
    repartition pass is pure wasted work — one full-data exchange instead
    of two. The repartition's block count survives as the shuffle's
    ``num_blocks`` (unless the shuffle already pins its own). SEEDED
    shuffles never collapse: their deterministic output depends on the
    exact input block boundaries the repartition would have produced."""

    def apply(self, plan: List[LogicalOp]) -> List[LogicalOp]:
        out: List[LogicalOp] = []
        for op in plan:
            prev = out[-1] if out else None
            if (isinstance(op, ShuffleOp) and op.kind == "random_shuffle"
                    and op.args.get("seed") is None
                    and isinstance(prev, ShuffleOp)
                    and prev.kind == "repartition"):
                args = dict(op.args)
                if not args.get("num_blocks"):
                    args["num_blocks"] = prev.args.get("num_blocks")
                out[-1] = ShuffleOp(op.name, "random_shuffle", args)
            else:
                out.append(op)
        return out


class FuseLimits(Rule):
    def apply(self, plan: List[LogicalOp]) -> List[LogicalOp]:
        out: List[LogicalOp] = []
        for op in plan:
            if (isinstance(op, LimitOp) and out
                    and isinstance(out[-1], LimitOp)):
                out[-1] = LimitOp(name="limit",
                                  limit=min(out[-1].limit, op.limit))
            else:
                out.append(op)
        return out


DEFAULT_RULES: List[Rule] = [
    EliminateRedundantShuffles(),
    CollapseRepartitionIntoShuffle(),
    FuseLimits(),
    OperatorFusionRule(),
]


class Optimizer:
    """Applies rules to a fixpoint (bounded passes), reference
    ``LogicalOptimizer`` role."""

    def __init__(self, rules: List[Rule] = None, max_passes: int = 5):
        self.rules = list(DEFAULT_RULES if rules is None else rules)
        self.max_passes = max_passes

    def optimize(self, plan: List[LogicalOp]) -> List[LogicalOp]:
        for _ in range(self.max_passes):
            before = _plan_signature(plan)
            for rule in self.rules:
                plan = rule.apply(plan)
            if _plan_signature(plan) == before:
                break
        return plan


def _plan_signature(plan: List[LogicalOp]) -> tuple:
    sig = []
    for op in plan:
        if isinstance(op, MapOp):
            sig.append(("map", op.name, id(op.fn), id(op.compute)))
        elif isinstance(op, ShuffleOp):
            sig.append(("shuffle", op.kind, tuple(sorted(op.args))))
        elif isinstance(op, LimitOp):
            sig.append(("limit", op.limit))
        else:
            sig.append((type(op).__name__,))
    return tuple(sig)


def plan_summary(plan: List[LogicalOp]) -> List[str]:
    """Human/golden-test readable plan: ['map:a->b', 'shuffle:sort', ...]"""
    out = []
    for op in plan:
        if isinstance(op, MapOp):
            kind = "actor_map" if op.compute is not None else "map"
            out.append(f"{kind}:{op.name}")
        elif isinstance(op, ShuffleOp):
            out.append(f"shuffle:{op.kind}")
        elif isinstance(op, LimitOp):
            out.append(f"limit:{op.limit}")
        else:
            out.append(type(op).__name__)
    return out
