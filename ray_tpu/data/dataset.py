"""Dataset: lazy logical plan over blocks, streaming-executed.

Role analog: ``python/ray/data/dataset.py`` + the logical-operator layer
(``data/_internal/logical/``). A Dataset is (source refs, list of logical
ops); every transform appends an op and returns a new Dataset; execution
happens on iteration/consumption through the streaming executor.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Dict, Iterator, List, Optional, Union

import numpy as np

import ray_tpu
from ray_tpu.data.block import (
    Block,
    batch_to_block,
    block_from_rows,
    block_metadata,
    block_num_rows,
    block_slice,
    block_to_batch,
    block_to_rows,
    concat_blocks,
)
from ray_tpu.data.execution import (
    ActorPoolStrategy,
    AllToAllOp,
    ExecutionOptions,
    LimitOp,
    MapOp,
    ShuffleOp,
    execute_streaming,
)


class Dataset:
    def __init__(self, source_refs: Any, ops: Optional[List[Any]] = None,
                 options: Optional[ExecutionOptions] = None):
        # source: a list of block refs, OR a zero-arg callable returning an
        # iterator of raw Blocks (lazy datasource, ``read_api`` ``lazy=``):
        # lazy blocks are generated + put per execution and the streaming
        # exchange frees them once consumed, so a dataset bigger than the
        # object store can flow through a shuffle without ever being
        # materialized up front
        self._source = (source_refs if callable(source_refs)
                        else list(source_refs))
        self._ops = list(ops or [])
        self._options = options or ExecutionOptions()

    # -- plan building ----------------------------------------------------

    def _with_op(self, op) -> "Dataset":
        return Dataset(self._source, self._ops + [op], self._options)

    def map_batches(
        self,
        fn: Callable,
        *,
        batch_size: Optional[int] = None,
        batch_format: str = "numpy",
        fn_kwargs: Optional[Dict[str, Any]] = None,
        compute: Optional["ActorPoolStrategy"] = None,
        fn_constructor_args: tuple = (),
        **_ignored,
    ) -> "Dataset":
        kwargs = fn_kwargs or {}
        is_class = isinstance(fn, type)
        if is_class and compute is None:
            compute = ActorPoolStrategy()  # classes imply actor compute

        def _map(block: Block, _state: Dict[str, Any] = {}) -> List[Block]:
            call = fn
            if is_class:
                # per-actor (or per-task) stateful callable: construct once
                if "obj" not in _state:
                    _state["obj"] = fn(*fn_constructor_args)
                call = _state["obj"]
            out: List[Block] = []
            n = block_num_rows(block)
            size = batch_size or n or 1
            for i in range(0, max(n, 1), size):
                piece = block_slice(block, i, min(i + size, n))
                if block_num_rows(piece) == 0 and n > 0:
                    continue
                res = call(block_to_batch(piece, batch_format), **kwargs)
                out.append(batch_to_block(res))
            return out

        return self._with_op(MapOp(name="map_batches", fn=_map,
                                   compute=compute))

    def map(self, fn: Callable[[Dict[str, Any]], Dict[str, Any]]) -> "Dataset":
        def _map(block: Block) -> List[Block]:
            return [block_from_rows([fn(r) for r in block_to_rows(block)])]

        return self._with_op(MapOp(name="map", fn=_map))

    def flat_map(self, fn: Callable[[Dict[str, Any]], List[Dict[str, Any]]]
                 ) -> "Dataset":
        def _map(block: Block) -> List[Block]:
            rows: List[Dict[str, Any]] = []
            for r in block_to_rows(block):
                rows.extend(fn(r))
            return [block_from_rows(rows)] if rows else []

        return self._with_op(MapOp(name="flat_map", fn=_map))

    def filter(self, fn: Callable[[Dict[str, Any]], bool]) -> "Dataset":
        def _map(block: Block) -> List[Block]:
            keep = [r for r in block_to_rows(block) if fn(r)]
            return [block_from_rows(keep)] if keep else []

        return self._with_op(MapOp(name="filter", fn=_map))

    def add_column(self, name: str, fn: Callable[[Block], np.ndarray]
                   ) -> "Dataset":
        def _map(block: Block) -> List[Block]:
            out = dict(block)
            out[name] = np.asarray(fn(block))
            return [out]

        return self._with_op(MapOp(name="add_column", fn=_map))

    def drop_columns(self, cols: List[str]) -> "Dataset":
        def _map(block: Block) -> List[Block]:
            return [{k: v for k, v in block.items() if k not in cols}]

        return self._with_op(MapOp(name="drop_columns", fn=_map))

    def select_columns(self, cols: List[str]) -> "Dataset":
        def _map(block: Block) -> List[Block]:
            return [{k: block[k] for k in cols}]

        return self._with_op(MapOp(name="select_columns", fn=_map))

    def rename_columns(self, mapping: Dict[str, str]) -> "Dataset":
        def _map(block: Block) -> List[Block]:
            return [{mapping.get(k, k): v for k, v in block.items()}]

        return self._with_op(MapOp(name="rename_columns", fn=_map))

    def random_shuffle(self, *, seed: Optional[int] = None,
                       num_blocks: Optional[int] = None) -> "Dataset":
        return self._with_op(ShuffleOp("random_shuffle", "random_shuffle",
                                       {"seed": seed,
                                        "num_blocks": num_blocks}))

    def repartition(self, num_blocks: int) -> "Dataset":
        return self._with_op(ShuffleOp("repartition", "repartition",
                                       {"num_blocks": num_blocks}))

    def sort(self, key: str, descending: bool = False,
             num_blocks: Optional[int] = None) -> "Dataset":
        """Global sort. ``num_blocks`` sets the number of reduce
        partitions (each streaming reducer materializes at most one
        partition — more partitions = flatter per-worker memory for
        out-of-core sorts); default one per input block."""
        return self._with_op(ShuffleOp("sort", "sort",
                                       {"key": key,
                                        "descending": descending,
                                        "num_blocks": num_blocks}))

    def limit(self, n: int) -> "Dataset":
        return self._with_op(LimitOp("limit", n))

    def union(self, *others: "Dataset") -> "Dataset":
        # materialize each side's plan into refs, then concatenate sources
        refs = list(self.iter_block_refs())
        for o in others:
            refs.extend(o.iter_block_refs())
        return Dataset(refs)

    def zip(self, other: "Dataset") -> "Dataset":
        left = concat_blocks([ray_tpu.get(r) for r in self.iter_block_refs()])
        right = concat_blocks([ray_tpu.get(r) for r in other.iter_block_refs()])
        if block_num_rows(left) != block_num_rows(right):
            raise ValueError("zip requires equal row counts")
        merged = dict(left)
        for k, v in right.items():
            merged[k if k not in merged else f"{k}_1"] = v
        return Dataset([ray_tpu.put(merged)])

    def groupby(self, key: str) -> "GroupedData":
        from ray_tpu.data.grouped import GroupedData

        return GroupedData(self, key)

    # -- execution --------------------------------------------------------

    def iter_block_refs(self) -> Iterator[Any]:
        return self._iter_with_recovery()

    def _iter_with_recovery(self) -> Iterator[Any]:
        """Execute the plan, re-executing it from lineage when an exchange
        reducer (or map-pool) actor dies before ANY output block was
        consumed. The plan's sources survive every execution — only
        ephemeral intermediates are freed — so a fresh run reproduces the
        result; past the first yield a failure must surface (a partially
        consumed stream cannot be transparently respliced)."""
        from ray_tpu import config as _config
        from ray_tpu.core.exceptions import ActorDiedError

        retries = int(_config.get("data_exchange_retries"))
        attempt = 0
        while True:
            source = (self._source() if callable(self._source)
                      else iter(self._source))
            stream = execute_streaming(source, self._ops, self._options)
            try:
                first = next(stream)
            except StopIteration:
                return
            except ActorDiedError:
                if attempt >= retries:
                    raise
                attempt += 1
                continue
            yield first
            yield from stream
            return

    def iter_blocks(self) -> Iterator[Block]:
        for ref in self.iter_block_refs():
            yield ray_tpu.get(ref)

    def materialize(self) -> "Dataset":
        return Dataset(list(self.iter_block_refs()))

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        for block in self.iter_blocks():
            yield from block_to_rows(block)

    def iter_batches(
        self,
        *,
        batch_size: int = 256,
        batch_format: str = "numpy",
        drop_last: bool = False,
        prefetch_batches: int = 1,
    ) -> Iterator[Any]:
        from ray_tpu.data.iterator import iter_batches_from_blocks

        return iter_batches_from_blocks(
            self.iter_blocks(), batch_size=batch_size,
            batch_format=batch_format, drop_last=drop_last,
            prefetch_batches=prefetch_batches)

    def iterator(self) -> "DataIterator":
        from ray_tpu.data.iterator import DataIterator

        return DataIterator(self)

    def streaming_split(self, n: int, *, equal: bool = True
                        ) -> List["DataIterator"]:
        """Split into n iterators for n training workers (reference
        ``Dataset.streaming_split`` used by Train's DataConfig)."""
        from ray_tpu.data.iterator import DataIterator

        return [DataIterator(self, split_index=i, num_splits=n)
                for i in range(n)]

    def split(self, n: int) -> List["Dataset"]:
        blocks = list(self.iter_block_refs())
        whole = concat_blocks([ray_tpu.get(r) for r in blocks])
        total = block_num_rows(whole)
        size = (total + n - 1) // n
        return [Dataset([ray_tpu.put(block_slice(whole, i * size,
                                                 min((i + 1) * size, total)))])
                for i in range(n)]

    # -- consumption ------------------------------------------------------

    def take(self, n: int = 20) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        for row in self.iter_rows():
            out.append(row)
            if len(out) >= n:
                break
        return out

    def take_all(self) -> List[Dict[str, Any]]:
        return list(self.iter_rows())

    def count(self) -> int:
        return sum(block_num_rows(b) for b in self.iter_blocks())

    def schema(self) -> Optional[Dict[str, str]]:
        for block in self.iter_blocks():
            if block:
                return {k: str(v.dtype) for k, v in block.items()}
        return None

    def columns(self) -> List[str]:
        s = self.schema()
        return list(s) if s else []

    def num_blocks(self) -> int:
        return sum(1 for _ in self.iter_block_refs())

    def size_bytes(self) -> int:
        return sum(block_metadata(b).size_bytes for b in self.iter_blocks())

    def to_pandas(self):
        from ray_tpu.data.block import block_to_pandas

        return block_to_pandas(concat_blocks(list(self.iter_blocks())))

    def to_arrow(self):
        """One pyarrow Table over all blocks (reference
        ``Dataset.to_arrow_refs`` role, materialized). Tensor columns
        (ndim > 1) become arrow list columns, matching write_parquet."""
        import pyarrow as pa

        block = concat_blocks(list(self.iter_blocks()))
        return pa.table({k: pa.array(list(v) if getattr(v, "ndim", 1) > 1
                                     else v)
                         for k, v in block.items()})

    def sum(self, col: str) -> float:
        return float(sum(b[col].sum() for b in self.iter_blocks() if col in b))

    def min(self, col: str) -> float:
        return float(min(b[col].min() for b in self.iter_blocks() if col in b))

    def max(self, col: str) -> float:
        return float(max(b[col].max() for b in self.iter_blocks() if col in b))

    def mean(self, col: str) -> float:
        total, count = 0.0, 0
        for b in self.iter_blocks():
            if col in b:
                total += float(b[col].sum())
                count += len(b[col])
        return total / max(count, 1)

    # -- writes -----------------------------------------------------------

    def write_parquet(self, path: str) -> None:
        import os

        import pyarrow as pa
        import pyarrow.parquet as pq

        os.makedirs(path, exist_ok=True)
        for i, block in enumerate(self.iter_blocks()):
            table = pa.table({k: list(v) if v.ndim > 1 else v
                              for k, v in block.items()})
            pq.write_table(table, f"{path}/part-{i:05d}.parquet")

    def write_csv(self, path: str) -> None:
        import os

        os.makedirs(path, exist_ok=True)
        from ray_tpu.data.block import block_to_pandas

        for i, block in enumerate(self.iter_blocks()):
            block_to_pandas(block).to_csv(f"{path}/part-{i:05d}.csv",
                                          index=False)

    def write_json(self, path: str) -> None:
        import os

        os.makedirs(path, exist_ok=True)
        from ray_tpu.data.block import block_to_pandas

        for i, block in enumerate(self.iter_blocks()):
            block_to_pandas(block).to_json(f"{path}/part-{i:05d}.json",
                                           orient="records", lines=True)

    def __repr__(self):
        ops = " -> ".join(op.name for op in self._ops) or "source"
        src = ("lazy source" if callable(self._source)
               else f"{len(self._source)} source blocks")
        return f"Dataset({src}, plan: {ops})"
