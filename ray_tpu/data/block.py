"""Blocks: the unit of data movement in ray_tpu.data.

Role analog: ``python/ray/data/block.py`` — a Dataset is a list of object
refs to Blocks. The reference standardizes on Arrow tables; here a block is
a dict of numpy arrays ("column batch") — the natural interchange for JAX
(zero-copy into ``jax.Array`` shards, no Arrow dependency on the hot path)
— with pandas/arrow conversion at the edges.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Iterable, List, Mapping, Optional, Union

import numpy as np

Block = Dict[str, np.ndarray]


@dataclass
class BlockMetadata:
    num_rows: int
    size_bytes: int
    schema: Optional[Dict[str, str]] = None


def block_from_rows(rows: Iterable[Mapping[str, Any]]) -> Block:
    rows = list(rows)
    if not rows:
        return {}
    if not isinstance(rows[0], Mapping):
        rows = [{"item": r} for r in rows]
    cols: Dict[str, List[Any]] = {k: [] for k in rows[0]}
    for r in rows:
        for k in cols:
            cols[k].append(r[k])
    return {k: np.asarray(v) for k, v in cols.items()}


def block_to_rows(block: Block) -> List[Dict[str, Any]]:
    if not block:
        return []
    keys = list(block)
    n = len(block[keys[0]])
    return [{k: block[k][i] for k in keys} for i in range(n)]


def block_num_rows(block: Block) -> int:
    if not block:
        return 0
    return len(next(iter(block.values())))


def block_size_bytes(block: Block) -> int:
    return sum(v.nbytes for v in block.values() if hasattr(v, "nbytes"))


def block_metadata(block: Block) -> BlockMetadata:
    return BlockMetadata(
        num_rows=block_num_rows(block),
        size_bytes=block_size_bytes(block),
        schema={k: str(v.dtype) for k, v in block.items()},
    )


def block_slice(block: Block, start: int, end: int) -> Block:
    return {k: v[start:end] for k, v in block.items()}


def block_take(block: Block, indices: np.ndarray) -> Block:
    return {k: v[indices] for k, v in block.items()}


def concat_blocks(blocks: List[Block]) -> Block:
    blocks = [b for b in blocks if block_num_rows(b)]
    if not blocks:
        return {}
    keys = list(blocks[0])
    return {k: np.concatenate([b[k] for b in blocks]) for k in keys}


def block_to_pandas(block: Block):
    import pandas as pd

    return pd.DataFrame({k: list(v) if v.ndim > 1 else v
                         for k, v in block.items()})


def block_from_pandas(df) -> Block:
    return {str(c): df[c].to_numpy() for c in df.columns}


def block_to_batch(block: Block, batch_format: str = "numpy"):
    if batch_format in ("numpy", "default"):
        return block
    if batch_format == "pandas":
        return block_to_pandas(block)
    if batch_format == "arrow":
        import pyarrow as pa

        return pa.table({k: v for k, v in block.items()})
    raise ValueError(f"unknown batch_format {batch_format!r}")


def batch_to_block(batch: Union[Block, Any]) -> Block:
    if isinstance(batch, dict):
        return {k: np.asarray(v) for k, v in batch.items()}
    # arrow check must precede pandas: pyarrow.Table has .columns too
    if hasattr(batch, "column_names"):  # arrow
        return {name: batch[name].to_numpy(zero_copy_only=False)
                for name in batch.column_names}
    if hasattr(batch, "columns"):  # pandas
        return block_from_pandas(batch)
    raise TypeError(f"cannot convert {type(batch)} to a block")
