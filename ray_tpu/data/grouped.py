"""GroupedData: groupby + aggregations.

Role analog: ``python/ray/data/grouped_data.py``. Aggregation is an
all-to-all (hash-group on the materialized stream), matching the
reference's shuffle-based groupby semantics.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List

import numpy as np

import ray_tpu
from ray_tpu.data.block import Block, block_take, concat_blocks


class GroupedData:
    def __init__(self, dataset, key: str):
        self._dataset = dataset
        self._key = key

    def _grouped(self) -> Dict[Any, Block]:
        whole = concat_blocks(list(self._dataset.iter_blocks()))
        if not whole:
            return {}
        keys = whole[self._key]
        order = np.argsort(keys, kind="stable")
        sorted_block = block_take(whole, order)
        sorted_keys = sorted_block[self._key]
        groups: Dict[Any, Block] = {}
        boundaries = np.flatnonzero(
            np.concatenate([[True], sorted_keys[1:] != sorted_keys[:-1]]))
        ends = np.concatenate([boundaries[1:], [len(sorted_keys)]])
        for start, end in zip(boundaries, ends):
            groups[sorted_keys[start].item()
                   if hasattr(sorted_keys[start], "item")
                   else sorted_keys[start]] = {
                k: v[start:end] for k, v in sorted_block.items()}
        return groups

    def _agg(self, cols_fn: Callable[[Any, Block], Dict[str, Any]]):
        from ray_tpu.data.block import block_from_rows
        from ray_tpu.data.dataset import Dataset

        rows: List[Dict[str, Any]] = []
        for key, block in self._grouped().items():
            rows.append({self._key: key, **cols_fn(key, block)})
        return Dataset([ray_tpu.put(block_from_rows(rows))])

    def count(self):
        from ray_tpu.data.block import block_num_rows

        return self._agg(lambda k, b: {"count()": block_num_rows(b)})

    def sum(self, col: str):
        return self._agg(lambda k, b: {f"sum({col})": float(b[col].sum())})

    def mean(self, col: str):
        return self._agg(lambda k, b: {f"mean({col})": float(b[col].mean())})

    def min(self, col: str):
        return self._agg(lambda k, b: {f"min({col})": float(b[col].min())})

    def max(self, col: str):
        return self._agg(lambda k, b: {f"max({col})": float(b[col].max())})

    def std(self, col: str):
        return self._agg(lambda k, b: {f"std({col})": float(b[col].std())})

    def aggregate(self, name: str, fn: Callable[[Block], Any]):
        return self._agg(lambda k, b: {name: fn(b)})

    def map_groups(self, fn: Callable[[Block], Block]):
        from ray_tpu.data.dataset import Dataset

        refs = [ray_tpu.put(fn(b)) for b in self._grouped().values()]
        from ray_tpu.data.block import block_num_rows

        return Dataset([r for r in refs])
