"""GroupedData: groupby + aggregations.

Role analog: ``python/ray/data/grouped_data.py``. Aggregation is a
DISTRIBUTED hash-partitioned exchange (VERDICT r3 #5): partition tasks
hash rows by key to reducers, each reducer groups + aggregates its
partition, and only the (small) aggregated rows return to the driver —
block bytes never materialize there (the round-2 version concatenated the
whole dataset in the driver process).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List

import numpy as np

import ray_tpu
from ray_tpu.data.block import Block, block_num_rows, block_take, concat_blocks


def _hash_assign(keys: np.ndarray, n_red: int) -> np.ndarray:
    """Per-row reducer assignment, identical in EVERY process. Python's
    ``hash()`` is salted per interpreter (workers are separate
    executables), which would scatter one key across reducers and return
    duplicate, split groups — use a keyed-nothing blake2 digest instead."""
    import hashlib

    if keys.dtype.kind in "iub":
        return (keys.astype(np.int64) % n_red + n_red) % n_red
    return np.asarray(
        [int.from_bytes(hashlib.blake2b(str(k).encode(),
                                        digest_size=8).digest(),
                        "little") % n_red
         for k in keys.tolist()], dtype=np.int64)


def _group_block(block: Block, key: str) -> List[tuple]:
    """(key value, sub-block) pairs of one partition, sorted by key."""
    if not block or block_num_rows(block) == 0:
        return []
    keys = block[key]
    order = np.argsort(keys, kind="stable")
    sorted_block = block_take(block, order)
    sorted_keys = sorted_block[key]
    out = []
    starts = np.flatnonzero(
        np.concatenate([[True], sorted_keys[1:] != sorted_keys[:-1]]))
    ends = np.concatenate([starts[1:], [len(sorted_keys)]])
    for start, end in zip(starts, ends):
        kv = sorted_keys[start]
        out.append((kv.item() if hasattr(kv, "item") else kv,
                    {k: v[start:end] for k, v in sorted_block.items()}))
    return out


def _partition_by_key(block: Block, key: str, n_red: int) -> List[Block]:
    n = block_num_rows(block)
    if n == 0:
        return [{} for _ in range(n_red)]
    assign = _hash_assign(block[key], n_red)
    return [{k: v[np.flatnonzero(assign == j)] for k, v in block.items()}
            for j in range(n_red)]


def _reduce_agg(key: str, cols_fn_blob: bytes, *parts: Block):
    """Group one hash partition and aggregate; returns (small) rows."""
    import cloudpickle as _cp

    cols_fn = _cp.loads(cols_fn_blob)
    merged = concat_blocks([p for p in parts if p and block_num_rows(p)])
    rows: List[Dict[str, Any]] = []
    for kv, sub in _group_block(merged, key):
        rows.append({key: kv, **cols_fn(kv, sub)})
    return rows


def _reduce_map_groups(key: str, fn_blob: bytes, *parts: Block):
    import cloudpickle as _cp

    fn = _cp.loads(fn_blob)
    merged = concat_blocks([p for p in parts if p and block_num_rows(p)])
    return [fn(sub) for _, sub in _group_block(merged, key)]


def _exchange_refs_with_recovery(kind: str, args: dict, dataset) -> List[Any]:
    """Drive one streaming groupby exchange to completion; a reducer-actor
    death re-runs the WHOLE exchange from the dataset's lineage (same
    recovery contract as ``Dataset._iter_with_recovery`` — the groupby
    entry points consume fully, so a restart can never duplicate output)."""
    from ray_tpu import config
    from ray_tpu.core.exceptions import ActorDiedError
    from ray_tpu.data.streaming import run_exchange

    retries = int(config.get("data_exchange_retries"))
    for attempt in range(retries + 1):
        try:
            return list(run_exchange(kind, dict(args),
                                     dataset.iter_block_refs()))
        except ActorDiedError:
            if attempt >= retries:
                raise
    raise AssertionError("unreachable")


class GroupedData:
    def __init__(self, dataset, key: str):
        self._dataset = dataset
        self._key = key

    @staticmethod
    def _streaming() -> bool:
        from ray_tpu import config

        return bool(config.get("data_streaming_exchange"))

    # -- streaming path (data/streaming.py engine) ------------------------

    def _agg_rows(self, kind: str, args: dict):
        """Run a streaming groupby exchange; only the (small) aggregated
        rows ever return to the driver."""
        from ray_tpu.data.block import block_from_rows
        from ray_tpu.data.dataset import Dataset

        rows: List[Dict[str, Any]] = []
        for ref in _exchange_refs_with_recovery(kind, args, self._dataset):
            rows.extend(ray_tpu.get(ref))
        rows.sort(key=lambda r: r[self._key])
        return Dataset([ray_tpu.put(block_from_rows(rows))])

    def _agg_specs(self, specs: List[tuple]):
        """Built-in aggregations as COMBINABLE (op, col, out_name) specs:
        the streaming reducer folds them into per-key accumulators, so the
        aggregation runs in O(distinct keys) memory at any dataset size."""
        if self._streaming():
            return self._agg_rows("groupby_agg",
                                  {"key": self._key, "specs": specs})

        def cols_fn(k, b, _specs=tuple(specs)):
            out = {}
            for op, col, name in _specs:
                if op == "count":
                    out[name] = block_num_rows(b)
                elif op == "sum":
                    out[name] = float(b[col].sum())
                elif op == "mean":
                    out[name] = float(b[col].mean())
                elif op == "min":
                    out[name] = float(b[col].min())
                elif op == "max":
                    out[name] = float(b[col].max())
                elif op == "std":
                    out[name] = float(b[col].std())
            return out

        return self._agg(cols_fn)

    # -- legacy one-shot exchange (RTPU_DATA_STREAMING_EXCHANGE=0) --------

    def _exchange(self, reduce_fn, blob: bytes) -> List[Any]:
        """Hash-partition the dataset's blocks and run one reduce task per
        partition; returns the reduce tasks' result refs."""
        refs = list(self._dataset.iter_block_refs())
        if not refs:
            return []
        n_red = max(1, min(len(refs), 8))
        part = ray_tpu.remote(num_returns=n_red)(_partition_by_key) \
            if n_red > 1 else ray_tpu.remote(
                lambda b, k, n: _partition_by_key(b, k, n)[0])
        parts = [part.remote(r, self._key, n_red) for r in refs]
        if n_red == 1:
            parts = [[p] for p in parts]
        red = ray_tpu.remote(reduce_fn)
        return [red.remote(self._key, blob,
                           *[parts[i][j] for i in range(len(parts))])
                for j in range(n_red)]

    def _agg(self, cols_fn: Callable[[Any, Block], Dict[str, Any]]):
        import cloudpickle as _cp

        from ray_tpu.data.block import block_from_rows
        from ray_tpu.data.dataset import Dataset

        out = self._exchange(_reduce_agg, _cp.dumps(cols_fn))
        rows: List[Dict[str, Any]] = []
        for part_rows in ray_tpu.get(out):
            rows.extend(part_rows)  # aggregated rows only: tiny
        rows.sort(key=lambda r: r[self._key])
        return Dataset([ray_tpu.put(block_from_rows(rows))])

    def count(self):
        return self._agg_specs([("count", None, "count()")])

    def sum(self, col: str):
        return self._agg_specs([("sum", col, f"sum({col})")])

    def mean(self, col: str):
        return self._agg_specs([("mean", col, f"mean({col})")])

    def min(self, col: str):
        return self._agg_specs([("min", col, f"min({col})")])

    def max(self, col: str):
        return self._agg_specs([("max", col, f"max({col})")])

    def std(self, col: str):
        return self._agg_specs([("std", col, f"std({col})")])

    def aggregate(self, name: str, fn: Callable[[Block], Any]):
        """Arbitrary per-group aggregation — not combinable, so the
        streaming reducer materializes each hash partition (only its own)
        at finish."""
        if self._streaming():
            import cloudpickle as _cp

            blob = _cp.dumps(lambda k, b, _fn=fn, _n=name: {_n: _fn(b)})
            return self._agg_rows("groupby_fn",
                                  {"key": self._key, "cols_fn_blob": blob})
        return self._agg(lambda k, b: {name: fn(b)})

    def map_groups(self, fn: Callable[[Block], Block]):
        import cloudpickle as _cp

        from ray_tpu.data.dataset import Dataset

        if self._streaming():
            refs = _exchange_refs_with_recovery(
                "groupby_groups",
                {"key": self._key, "fn_blob": _cp.dumps(fn)},
                self._dataset)
            return Dataset(refs)

        out = self._exchange(_reduce_map_groups, _cp.dumps(fn))

        @ray_tpu.remote(num_returns="streaming")
        def _split(blocks):
            for b in blocks:
                yield b

        refs: List[Any] = []
        for r in out:
            refs.extend(_split.remote(r))
        return Dataset(refs)
