"""ray_tpu.data — streaming distributed datasets feeding pjit programs.

Role analog: ``python/ray/data`` (SURVEY §2.5, §3.7). Same architecture in
compact form: lazy logical plan → fused map stages → streaming execution
over the task runtime with bounded in-flight backpressure; all-to-all ops
are barriers. TPU-native addition: ``DataIterator.iter_jax_batches`` yields
mesh-sharded ``jax.Array`` batches (the ingest path of JaxTrainer).
"""

from ray_tpu.data.block import Block, BlockMetadata
from ray_tpu.data.dataset import Dataset
from ray_tpu.data.execution import (ActorPoolStrategy,
                                    BackpressurePolicy,
                                    ConcurrencyCapBackpressurePolicy,
                                    ExecutionOptions,
                                    StoreMemoryBackpressurePolicy)
from ray_tpu.data.optimizer import (CollapseRepartitionIntoShuffle,
                                    DEFAULT_RULES, EliminateRedundantShuffles,
                                    FuseLimits, OperatorFusionRule, Optimizer,
                                    Rule, plan_summary)
from ray_tpu.data.grouped import GroupedData
from ray_tpu.data.iterator import DataIterator
from ray_tpu.data.read_api import (
    from_arrow,
    from_items,
    from_numpy,
    from_pandas,
    range,
    range_tensor,
    read_binary_files,
    read_images,
    read_csv,
    read_json,
    read_numpy,
    read_parquet,
    read_text,
)

__all__ = [
    "Block",
    "BlockMetadata",
    "Dataset",
    "DataIterator",
    "ExecutionOptions",
    "ActorPoolStrategy",
    "BackpressurePolicy",
    "ConcurrencyCapBackpressurePolicy",
    "StoreMemoryBackpressurePolicy",
    "Optimizer",
    "Rule",
    "DEFAULT_RULES",
    "OperatorFusionRule",
    "EliminateRedundantShuffles",
    "CollapseRepartitionIntoShuffle",
    "FuseLimits",
    "plan_summary",
    "GroupedData",
    "from_arrow",
    "from_items",
    "from_numpy",
    "from_pandas",
    "range",
    "range_tensor",
    "read_binary_files",
    "read_images",
    "read_csv",
    "read_json",
    "read_numpy",
    "read_parquet",
    "read_text",
]
