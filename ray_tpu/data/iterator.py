"""DataIterator: batch iteration with prefetch + JAX-native output.

Role analog: ``python/ray/data/iterator.py`` + the prefetching batcher
(``_internal/block_batching/iter_batches.py``). TPU-native additions:
``iter_jax_batches`` yields device-placed ``jax.Array`` batches (optionally
sharded over a mesh's data axes), which is the ingest path Train's
DataConfig uses — the host→HBM copy of batch i+1 overlaps the step on
batch i via a one-deep prefetch.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Dict, Iterator, Optional

import numpy as np

from ray_tpu.data.block import (
    Block,
    block_num_rows,
    block_slice,
    block_to_batch,
    concat_blocks,
)


def iter_batches_from_blocks(
    blocks: Iterator[Block],
    *,
    batch_size: int = 256,
    batch_format: str = "numpy",
    drop_last: bool = False,
    prefetch_batches: int = 1,
) -> Iterator[Any]:
    """Re-chunk a block stream into exact-size batches; prefetch on a thread."""

    def batcher() -> Iterator[Block]:
        carry: Optional[Block] = None
        for block in blocks:
            merged = concat_blocks([carry, block]) if carry else block
            n = block_num_rows(merged)
            i = 0
            while n - i >= batch_size:
                yield block_slice(merged, i, i + batch_size)
                i += batch_size
            carry = block_slice(merged, i, n) if i < n else None
        if carry and not drop_last and block_num_rows(carry):
            yield carry

    source = batcher()
    if prefetch_batches <= 0:
        for b in source:
            yield block_to_batch(b, batch_format)
        return

    q: "queue.Queue" = queue.Queue(maxsize=prefetch_batches)
    DONE, ERROR = object(), object()

    def producer():
        try:
            for b in source:
                q.put(b)
            q.put(DONE)
        except BaseException as e:  # noqa: BLE001
            q.put((ERROR, e))

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    while True:
        item = q.get()
        if item is DONE:
            return
        if isinstance(item, tuple) and item and item[0] is ERROR:
            raise item[1]
        yield block_to_batch(item, batch_format)


class DataIterator:
    """Per-consumer view of a Dataset (reference ``DataIterator``); with
    ``split_index``/``num_splits`` set it consumes a round-robin share of
    blocks (the ``streaming_split`` contract for per-worker ingest)."""

    def __init__(self, dataset, split_index: int = 0, num_splits: int = 1):
        self._dataset = dataset
        self._split = split_index
        self._num_splits = num_splits

    def _blocks(self) -> Iterator[Block]:
        import ray_tpu

        for i, ref in enumerate(self._dataset.iter_block_refs()):
            if self._num_splits <= 1 or i % self._num_splits == self._split:
                yield ray_tpu.get(ref)

    def iter_batches(self, *, batch_size: int = 256,
                     batch_format: str = "numpy",
                     drop_last: bool = False,
                     prefetch_batches: int = 1) -> Iterator[Any]:
        return iter_batches_from_blocks(
            self._blocks(), batch_size=batch_size, batch_format=batch_format,
            drop_last=drop_last, prefetch_batches=prefetch_batches)

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        from ray_tpu.data.block import block_to_rows

        for b in self._blocks():
            yield from block_to_rows(b)

    def iter_jax_batches(
        self,
        *,
        batch_size: int = 256,
        drop_last: bool = True,
        dtypes: Optional[Dict[str, Any]] = None,
        mesh=None,
        prefetch_batches: int = 1,
    ) -> Iterator[Dict[str, Any]]:
        """Yield batches as device-placed jax.Arrays.

        With ``mesh``, batches are sharded over the mesh's data axes
        (dp/fsdp) — the global-array ingest path for pjit training steps.
        """
        import jax

        sharding = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            data_axes = tuple(a for a in ("dcn", "dp", "fsdp")
                              if a in mesh.axis_names)
            sharding = NamedSharding(mesh, P(data_axes or None))

        for batch in self.iter_batches(batch_size=batch_size,
                                       batch_format="numpy",
                                       drop_last=drop_last,
                                       prefetch_batches=prefetch_batches):
            out = {}
            for k, v in batch.items():
                if dtypes and k in dtypes:
                    v = v.astype(dtypes[k])
                out[k] = jax.device_put(v, sharding) if sharding is not None \
                    else jax.device_put(v)
            yield out

    def iter_torch_batches(self, *, batch_size: int = 256,
                           drop_last: bool = False,
                           prefetch_batches: int = 1) -> Iterator[Any]:
        """CPU-torch compatibility (reference ``iter_torch_batches``)."""
        import torch

        for batch in self.iter_batches(batch_size=batch_size,
                                       batch_format="numpy",
                                       drop_last=drop_last,
                                       prefetch_batches=prefetch_batches):
            yield {k: torch.as_tensor(np.ascontiguousarray(v))
                   for k, v in batch.items()}

    def materialize(self):
        return self._dataset.materialize()

    def stats(self) -> str:
        return repr(self._dataset)
