"""Streaming all-to-all exchange engine for ray_tpu.data.

Role analog: the reference's push-based shuffle / exchange operators
(``python/ray/data/_internal/planner/exchange/`` executed by the streaming
executor) — the piece that lets sort/shuffle/repartition/groupby run over
datasets LARGER than the object store. The legacy exchange
(``execution._run_shuffle_tasks``) dispatches every partition task at once
and hands every partition block to one reduce task per reducer, so the
whole partitioned dataset exists in the store simultaneously. This engine
replaces that barrier with a pipeline:

- **map side**: one partition task per input block emits one block per
  logical reduce partition (``num_returns=n_red``), dispatched under a
  bounded blocks-in-flight window;
- **scheduler** (driver side): as each partition task finishes, its
  per-partition blocks are forwarded to reducer ACTORS as actor calls (the
  block travels by ref; the runtime resolves it on the reducer's node) and
  the source blocks are freed (:func:`ray_tpu.free`) the moment every
  reducer acked — exchange intermediates never accumulate;
- **reduce side**: each reducer actor owns ``n_red / R`` logical
  partitions. Sort reducers buffer rows and flush SORTED RUNS to the
  object store when the buffer passes ``data_exchange_run_bytes`` (the
  store's spill path moves runs to disk under memory pressure) and
  k-way-merge the runs at finish; shuffle/repartition reducers stage
  incoming blocks back into the store and only materialize their own
  partition at finish; combinable groupby aggregations fold into per-key
  accumulators and never materialize at all.

Backpressure: at most ``data_exchange_inflight`` partition-output blocks
are unconsumed (not yet acked by a reducer) at any moment; the scheduler
stops dispatching partition tasks while over the bound. There is no global
barrier for random_shuffle/groupby; sort and repartition take a barrier on
input REFS only (sample boundaries / row offsets), never on block bytes.

Everything here uses the public task/actor/object API only (CLAUDE.md
seam), including :func:`ray_tpu.free` for eager intermediate reclamation.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np

import ray_tpu
from ray_tpu import config
from ray_tpu.data.block import (
    Block,
    block_num_rows,
    block_size_bytes,
    block_slice,
    block_take,
    concat_blocks,
)

#: instrumentation for tests/debugging: counters of the most recently
#: finished exchange (max blocks in flight seen, parts, bytes, ...)
_LAST_EXCHANGE_STATS: Dict[str, Any] = {}


def _exchange_metrics():
    """Engine metrics (reference data-metrics role), defined centrally in
    util/metric_defs.py; registered on first exchange so a /metrics
    scrape during a run shows the live values. metric_defs.get caches
    and survives clear_registry, so the accessor just rebuilds."""
    from ray_tpu.util import metric_defs as md

    return {
        "in_flight": md.get("rtpu_data_exchange_blocks_in_flight"),
        "queue_depth": md.get("rtpu_data_exchange_reducer_queue_depth"),
        "bytes": md.get("rtpu_data_exchange_bytes_total"),
        "blocks": md.get("rtpu_data_exchange_blocks_total"),
    }


# ---------------------------------------------------------------------------
# map side: partition functions (run as tasks)
# ---------------------------------------------------------------------------

def _exchange_partition(block: Block, n_red: int, kind: str, args: dict,
                        part_idx: int) -> List[Block]:
    """Split one input block into ``n_red`` per-partition blocks."""
    from ray_tpu.util import tracing

    # map-stage span: with tracing armed, each exchange stage shows up on
    # the unified timeline as map -> (forwarded actor calls) -> reduce
    with tracing.span("data.exchange::map",
                      {"kind": kind, "part": part_idx}):
        if kind.startswith("groupby"):
            from ray_tpu.data.grouped import _partition_by_key

            return _partition_by_key(block, args["key"], n_red)
        from ray_tpu.data.execution import _shuffle_partition

        return _shuffle_partition(block, n_red, kind, args, part_idx)


# ---------------------------------------------------------------------------
# reduce side: the reducer actor
# ---------------------------------------------------------------------------

def _copy_block(block: Block) -> Block:
    """Deep-copy a block out of its zero-copy shm views — buffered rows
    must not pin the source segment (the whole point is freeing it)."""
    return {k: np.array(v, copy=True) for k, v in block.items()}


def _chunk_rows(blocks: List[Block], target_rows: int) -> Iterator[Block]:
    """Re-chunk a sequence of blocks into ~target_rows output blocks."""
    carry: List[Block] = []
    rows = 0
    for b in blocks:
        n = block_num_rows(b)
        if not n:
            continue
        carry.append(b)
        rows += n
        while rows >= target_rows:
            merged = concat_blocks(carry)
            yield block_slice(merged, 0, target_rows)
            rest = block_slice(merged, target_rows, rows)
            carry = [rest] if block_num_rows(rest) else []
            rows -= target_rows
    if rows:
        yield concat_blocks(carry)


def _merge_sorted_blocks(blocks: List[Block], key: str,
                         window: int = 65536) -> Iterator[Block]:
    """K-way merge of ascending-sorted blocks, vectorized: each step picks
    the smallest "window-end key" (pivot) across live runs, consumes every
    row <= pivot from every run (searchsorted), and sorts that bounded
    slice. Peak memory is O(runs * window), never the partition size."""
    blocks = [b for b in blocks if block_num_rows(b)]
    cursors = [0] * len(blocks)
    sizes = [block_num_rows(b) for b in blocks]
    while True:
        live = [i for i in range(len(blocks)) if cursors[i] < sizes[i]]
        if not live:
            return
        if len(live) == 1:
            i = live[0]
            yield block_slice(blocks[i], cursors[i], sizes[i])
            cursors[i] = sizes[i]
            continue
        pivot = min(blocks[i][key][min(cursors[i] + window, sizes[i]) - 1]
                    for i in live)
        parts = []
        for i in live:
            keys = blocks[i][key]
            hi = cursors[i] + int(np.searchsorted(
                keys[cursors[i]:sizes[i]], pivot, side="right"))
            if hi > cursors[i]:
                parts.append(block_slice(blocks[i], cursors[i], hi))
                cursors[i] = hi
        merged = concat_blocks(parts)
        order = np.argsort(merged[key], kind="stable")
        yield block_take(merged, order)


# combinable groupby aggregations: (op, col, out_name) specs fold into
# tiny per-key accumulators, so an aggregation over any dataset size runs
# in O(distinct keys) reducer memory
_COMBINABLE_OPS = ("count", "sum", "min", "max", "mean", "std")


def _acc_update(op: str, cur, sub: Block, col: Optional[str]):
    n = block_num_rows(sub)
    v = sub[col] if col else None
    if op == "count":
        return (cur or 0) + n
    if op == "sum":
        return (cur or 0.0) + float(v.sum())
    if op == "min":
        m = float(v.min())
        return m if cur is None else min(cur, m)
    if op == "max":
        m = float(v.max())
        return m if cur is None else max(cur, m)
    if op == "mean":
        c = cur or (0, 0.0)
        return (c[0] + n, c[1] + float(v.sum()))
    if op == "std":
        c = cur or (0, 0.0, 0.0)
        v64 = v.astype(np.float64)
        return (c[0] + n, c[1] + float(v64.sum()),
                c[2] + float((v64 * v64).sum()))
    raise ValueError(op)


def _acc_finalize(op: str, cur):
    if op == "count":
        return int(cur or 0)
    if op == "sum":
        return float(cur or 0.0)
    if op in ("min", "max"):
        return cur
    if op == "mean":
        return cur[1] / max(cur[0], 1)
    if op == "std":
        n, s, ss = cur
        mean = s / max(n, 1)
        return float(np.sqrt(max(ss / max(n, 1) - mean * mean, 0.0)))
    raise ValueError(op)


class _ExchangeReducer:
    """One reducer actor owning several logical reduce partitions.

    ``add_block`` receives partition blocks BY VALUE (the runtime resolves
    the forwarded ref on this node) and either folds them (combinable
    groupby), buffers copies + flushes sorted runs to the store (sort), or
    stages them back into the store as refs (shuffle/repartition/generic
    groupby) so its own heap stays bounded until ``finish``. ``finish`` is
    a streaming generator: output blocks flow to consumers as they are
    produced."""

    def __init__(self, kind: str, args_blob: bytes):
        import cloudpickle as _cp

        self._kind = kind
        self._args = _cp.loads(args_blob)
        self._parts: Dict[int, dict] = {}
        self._run_bytes = int(config.get("data_exchange_run_bytes"))
        self._target_rows = int(config.get("data_exchange_target_rows"))

    def _state(self, part: int) -> dict:
        st = self._parts.get(part)
        if st is None:
            st = {"runs": [], "held": [], "buf": [], "buf_bytes": 0,
                  "accs": {}}
            self._parts[part] = st
        return st

    # -- streaming ingest -------------------------------------------------

    def add_block(self, part: int, order_key: int,
                  block: Block) -> Tuple[int, int]:
        """Consume one partition block; returns (rows, bytes) as the ack
        the scheduler's backpressure window waits on."""
        from ray_tpu.util import tracing

        with tracing.span("data.exchange::reduce",
                          {"kind": self._kind, "part": part}):
            return self._add_block_inner(part, order_key, block)

    def _add_block_inner(self, part: int, order_key: int,
                         block: Block) -> Tuple[int, int]:
        st = self._state(part)
        rows = block_num_rows(block)
        nbytes = block_size_bytes(block)
        if rows == 0:
            return 0, 0
        if self._kind == "sort":
            st["buf"].append(_copy_block(block))
            st["buf_bytes"] += nbytes
            if st["buf_bytes"] >= self._run_bytes:
                self._flush_run(st)
        elif self._kind == "groupby_agg":
            from ray_tpu.data.grouped import _group_block

            for kv, sub in _group_block(block, self._args["key"]):
                accs = st["accs"].setdefault(
                    kv, [None] * len(self._args["specs"]))
                for si, (op, col, _name) in enumerate(self._args["specs"]):
                    accs[si] = _acc_update(op, accs[si], sub, col)
        else:
            # shuffle/repartition/groupby_fn/groupby_groups: stage the
            # block back into the store (it spills under pressure) and
            # keep only the ref; (order_key, ref) lets finish reassemble
            # in INPUT order, which repartition's order-preservation and
            # seeded shuffles' determinism both need
            st["held"].append((order_key, ray_tpu.put(_copy_block(block))))
        return rows, nbytes

    def _flush_run(self, st: dict) -> None:
        merged = concat_blocks(st["buf"])
        st["buf"] = []
        st["buf_bytes"] = 0
        order = np.argsort(merged[self._args["key"]], kind="stable")
        st["runs"].append(ray_tpu.put(block_take(merged, order)))

    def _assemble(self, st: dict) -> Block:
        """Materialize this partition (and only this partition) in input
        order; frees the staged refs as it goes."""
        held = sorted(st["held"], key=lambda t: t[0])
        st["held"] = []
        blocks = []
        for _, ref in held:
            blocks.append(_copy_block(ray_tpu.get(ref)))
            ray_tpu.free(ref)
        return concat_blocks(blocks)

    # -- finish: stream this partition's output ---------------------------

    def finish(self, part: int):
        st = self._state(part)
        kind = self._kind
        if kind == "sort":
            yield from self._finish_sort(st)
        elif kind == "random_shuffle":
            merged = self._assemble(st)
            n = block_num_rows(merged)
            if n:
                seed = self._args.get("seed")
                rng = np.random.default_rng(
                    None if seed is None else int(seed) * 9176 + part)
                merged = block_take(merged, rng.permutation(n))
                yield from _chunk_rows([merged], self._target_rows)
        elif kind == "repartition":
            # exactly one output block per logical partition: the
            # num_blocks contract
            merged = self._assemble(st)
            yield merged
        elif kind == "groupby_agg":
            key = self._args["key"]
            rows = []
            for kv, accs in st["accs"].items():
                row = {key: kv}
                for (op, _col, name), acc in zip(self._args["specs"], accs):
                    row[name] = _acc_finalize(op, acc)
                rows.append(row)
            yield rows
        elif kind == "groupby_fn":
            import cloudpickle as _cp

            from ray_tpu.data.grouped import _group_block

            cols_fn = _cp.loads(self._args["cols_fn_blob"])
            key = self._args["key"]
            merged = self._assemble(st)
            yield [{key: kv, **cols_fn(kv, sub)}
                   for kv, sub in _group_block(merged, key)]
        elif kind == "groupby_groups":
            import cloudpickle as _cp

            from ray_tpu.data.grouped import _group_block

            fn = _cp.loads(self._args["fn_blob"])
            merged = self._assemble(st)
            for _kv, sub in _group_block(merged, self._args["key"]):
                yield fn(sub)
        else:
            raise ValueError(kind)
        self._parts.pop(part, None)

    def _finish_sort(self, st: dict):
        if st["buf"]:
            self._flush_run(st)
        runs = st["runs"]
        st["runs"] = []
        key = self._args["key"]
        blocks = [ray_tpu.get(r) for r in runs]
        merge = _chunk_rows(_merge_sorted_blocks(blocks, key),
                            self._target_rows)
        if not self._args.get("descending"):
            for out in merge:
                yield out
        else:
            # runs are stored ascending (searchsorted needs that, and it
            # stays dtype-generic — strings sort too); a descending
            # partition is the ascending merge emitted back-to-front, so
            # stage the merged chunks as refs and replay them reversed
            staged = [ray_tpu.put(out) for out in merge]
            for ref in reversed(staged):
                b = ray_tpu.get(ref)
                yield {k: v[::-1].copy() for k, v in b.items()}
                ray_tpu.free(ref)
        del blocks
        if runs:
            ray_tpu.free(runs)


# ---------------------------------------------------------------------------
# driver-side scheduler
# ---------------------------------------------------------------------------

class _PendingPart:
    __slots__ = ("refs", "input_ref", "idx", "forwarded", "unacked")

    def __init__(self, refs, input_ref, idx):
        self.refs = refs
        self.input_ref = input_ref
        self.idx = idx
        self.forwarded = False
        self.unacked = 0


def run_exchange(kind: str, args: Dict[str, Any],
                 stream: Iterator[Any]) -> Iterator[Any]:
    """Execute one streaming exchange; yields output refs (sort: globally
    ordered across partitions; repartition: exactly ``num_blocks`` blocks;
    groupby kinds: one ref per reduce partition / group)."""
    yield from _ExchangeScheduler(kind, dict(args)).run(stream)


class _ExchangeScheduler:
    def __init__(self, kind: str, args: Dict[str, Any]):
        self.kind = kind
        self.args = args
        self.max_inflight = max(1, int(config.get("data_exchange_inflight")))
        self.max_reducers = max(1, int(config.get("data_exchange_reducers")))
        self.stats = {"kind": kind, "parts": 0, "blocks": 0, "bytes": 0,
                      "max_in_flight_seen": 0, "partitions": 0,
                      "reducers": 0}
        self._reducers: List[Any] = []

    # -- prologues --------------------------------------------------------

    def _prologue(self, stream):
        """Kind-specific setup. Sort and repartition need a barrier on
        input REFS (boundary sampling / global row offsets) — block bytes
        stay distributed; random_shuffle and groupby start partitioning
        the moment the first upstream block lands."""
        from ray_tpu.data.execution import (repartition_layout,
                                            sample_sort_boundaries)

        args = self.args
        if self.kind == "sort":
            refs = list(stream)
            self.n_red = self._n_red_for(len(refs))
            args.update(sample_sort_boundaries(
                refs, args["key"], bool(args.get("descending")),
                self.n_red))
            self.offsets = None
            return iter(refs)
        if self.kind == "repartition":
            refs = list(stream)
            self.n_red = max(1, int(args.get("num_blocks") or len(refs) or 1))
            args["target_size"], self.offsets = repartition_layout(
                refs, self.n_red)
            return iter(refs)
        # streaming kinds: partition count fixed up front, input unknown
        if self.kind == "random_shuffle":
            self.n_red = max(1, int(args.get("num_blocks")
                                    or self.max_reducers))
        else:  # groupby_*
            self.n_red = max(1, int(args.get("num_partitions")
                                    or 2 * self.max_reducers))
        self.offsets = None
        return stream

    def _n_red_for(self, n_inputs: int) -> int:
        return max(1, int(self.args.get("num_blocks") or n_inputs or 1))

    # -- scheduling loop --------------------------------------------------

    def run(self, stream: Iterator[Any]) -> Iterator[Any]:
        from ray_tpu.data import execution as _ex

        stream = self._prologue(stream)
        n_red = self.n_red
        self.stats["partitions"] = n_red
        m = _exchange_metrics()

        if n_red > 1:
            part_task = ray_tpu.remote(
                num_returns=n_red)(_exchange_partition)
        else:
            part_task = ray_tpu.remote(
                lambda b, n, k, a, i: _exchange_partition(b, n, k, a, i)[0])

        pending: deque = deque()      # dispatched partition tasks
        acks: Dict[Any, tuple] = {}   # ack ref -> (_PendingPart, owner idx)
        per_owner_depth: Dict[int, int] = {}
        exhausted = False
        input_idx = 0
        max_part_tasks = max(2, self.max_inflight // max(1, n_red))

        def in_flight() -> int:
            return (n_red * sum(1 for p in pending if not p.forwarded)
                    + sum(p.unacked for p in pending))

        def dispatch_one() -> bool:
            nonlocal exhausted, input_idx
            if exhausted:
                return False
            try:
                ref = next(stream)
            except StopIteration:
                exhausted = True
                return False
            a = dict(self.args)
            if self.offsets is not None:
                a["global_start"] = int(self.offsets[input_idx])
            out = part_task.remote(ref, n_red, self.kind, a, input_idx)
            refs = out if n_red > 1 else [out]
            pending.append(_PendingPart(refs, ref, input_idx))
            input_idx += 1
            self.stats["parts"] += 1
            return True

        def forward(p: _PendingPart) -> None:
            self._ensure_reducers()
            for j, r in enumerate(p.refs):
                owner = j % len(self._reducers)
                ack = self._reducers[owner].add_block.remote(j, p.idx, r)
                acks[ack] = (p, owner)
                per_owner_depth[owner] = per_owner_depth.get(owner, 0) + 1
                m["queue_depth"].set(per_owner_depth[owner],
                                     {"reducer": str(owner)})
            p.forwarded = True
            p.unacked = len(p.refs)

        def retire_ack(ack) -> None:
            from ray_tpu.util import failpoints

            p, owner = acks.pop(ack)
            # chaos site: ack retirement — delay throttles the window
            # (backpressure under a slow driver); raise simulates a
            # reducer-side ingest failure surfacing here
            failpoints.hit("data.exchange.ack", owner)
            rows, nbytes = ray_tpu.get(ack)  # raises on reducer error
            self.stats["blocks"] += 1
            self.stats["bytes"] += nbytes
            m["blocks"].inc(tags={"kind": self.kind})
            if nbytes:
                m["bytes"].inc(nbytes, tags={"kind": self.kind})
            per_owner_depth[owner] -= 1
            m["queue_depth"].set(per_owner_depth[owner],
                                 {"reducer": str(owner)})
            p.unacked -= 1
            if p.unacked == 0:
                # every reducer consumed its slice: reclaim the exchange
                # intermediates now (and the input block too when the
                # executor owns it)
                ray_tpu.free(p.refs)
                if _ex.is_ephemeral(p.input_ref):
                    _ex.unmark_ephemeral(p.input_ref)
                    ray_tpu.free(p.input_ref)
                p.refs = []
                p.input_ref = None
                pending.remove(p)

        def sample_gauges() -> None:
            fl = in_flight()
            self.stats["max_in_flight_seen"] = max(
                self.stats["max_in_flight_seen"], fl)
            m["in_flight"].set(fl)
            # spill_dir_bytes is NOT sampled here: the StoreClient
            # collector owns that gauge and runs right before every
            # snapshot, so a second writer could never be observed

        try:
            while True:
                progressed = 0
                # dispatch partition tasks under both windows (always let
                # one run when the pipe is empty, else n_red > window
                # would deadlock)
                while ((in_flight() + n_red <= self.max_inflight
                        or not pending)
                       and sum(1 for p in pending
                               if not p.forwarded) < max_part_tasks
                       and dispatch_one()):
                    progressed += 1
                # forward completed partition tasks
                waitable = [p.refs[0] for p in pending if not p.forwarded]
                if waitable:
                    ready, _ = ray_tpu.wait(
                        waitable, num_returns=len(waitable), timeout=0)
                    ready_set = set(ready)
                    for p in list(pending):
                        if not p.forwarded and p.refs[0] in ready_set:
                            forward(p)
                            progressed += 1
                # retire ready acks
                if acks:
                    ready, _ = ray_tpu.wait(list(acks), timeout=0,
                                            num_returns=len(acks))
                    for ack in ready:
                        retire_ack(ack)
                        progressed += 1
                sample_gauges()
                if exhausted and not pending:
                    break
                if not progressed:
                    watch = [p.refs[0] for p in pending
                             if not p.forwarded] + list(acks)
                    if watch:
                        ray_tpu.wait(watch, num_returns=1, timeout=5)
            # reduce epilogue: stream every partition's output in
            # partition order (sort's global order depends on it); all
            # generators are kicked off first so reducers run concurrently
            if self._reducers:
                gens = []
                for j in range(n_red):
                    owner = self._reducers[j % len(self._reducers)]
                    gens.append(owner.finish.options(
                        num_returns="streaming").remote(j))
                for gen in gens:
                    for ref in gen:
                        yield ref
        finally:
            m["in_flight"].set(0)
            for i in range(len(self._reducers)):
                m["queue_depth"].set(0, {"reducer": str(i)})
            for red in self._reducers:
                try:
                    ray_tpu.kill(red)
                except Exception:
                    pass
            self.stats["reducers"] = len(self._reducers)
            _LAST_EXCHANGE_STATS.clear()
            _LAST_EXCHANGE_STATS.update(self.stats)

    def _ensure_reducers(self) -> None:
        if self._reducers:
            return
        import cloudpickle as _cp

        blob = _cp.dumps(self.args)
        cls = ray_tpu.remote(_ExchangeReducer)
        n = min(self.max_reducers, self.n_red)
        # num_cpus=0: reducers are mostly-idle accumulators; holding a CPU
        # slot each would starve the partition tasks on small boxes
        self._reducers = [cls.options(num_cpus=0).remote(self.kind, blob)
                          for _ in range(n)]
