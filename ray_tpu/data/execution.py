"""Streaming execution of logical plans over the task runtime.

Role analog: ``python/ray/data/_internal/execution/streaming_executor.py:48``
and its Topology loop (``streaming_executor_state.py``). Same ideas, compact
form: logical map-ish operators are **fused** into one task per block
(reference optimizer's fusion rule), blocks flow through the fused pipeline
as object refs with a bounded number of in-flight tasks (backpressure), and
all-to-all ops (shuffle/sort/repartition/groupby) are barriers that
materialize their input refs.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

import ray_tpu
from ray_tpu.data.block import (
    Block,
    block_num_rows,
    block_slice,
    block_take,
    concat_blocks,
)


# ---------------------------------------------------------------------------
# Logical operators
# ---------------------------------------------------------------------------

@dataclass
class MapOp:
    """Block -> List[Block] transform; fusible with neighbors.

    ``compute``: None runs each block as a task; an ActorPoolStrategy
    runs blocks on a warm autoscaling actor pool (expensive per-block
    setup like model weights loads once per actor)."""

    name: str
    fn: Callable[[Block], List[Block]]
    compute: Optional["ActorPoolStrategy"] = None


@dataclass
class ActorPoolStrategy:
    """Reference ``ActorPoolMapOperator`` role: min_size warm actors,
    growing to max_size while the input queue is deep."""

    min_size: int = 1
    max_size: int = 4
    max_tasks_in_flight_per_actor: int = 2


@dataclass
class AllToAllOp:
    """Barrier op consuming all blocks at once (driver-side; only for
    custom user fns — the built-in exchanges use ShuffleOp)."""

    name: str
    fn: Callable[[List[Block]], List[Block]]


@dataclass
class ShuffleOp:
    """Distributed all-to-all (reference exchange ops under
    ``data/_internal/planner/exchange/``): partition tasks emit one block
    per reducer, reduce tasks consume the refs — block bytes NEVER pass
    through the driver. ``kind``: random_shuffle | repartition | sort."""

    name: str
    kind: str
    args: Dict[str, Any] = field(default_factory=dict)


@dataclass
class LimitOp:
    name: str
    limit: int


LogicalOp = Any  # MapOp | AllToAllOp | ShuffleOp | LimitOp


def fuse_ops(ops: List[LogicalOp]) -> List[LogicalOp]:
    """Merge consecutive MapOps into single fused stages (the reference's
    OperatorFusionRule): one task per block runs the whole chain."""
    fused: List[LogicalOp] = []
    for op in ops:
        if (isinstance(op, MapOp) and fused
                and isinstance(fused[-1], MapOp)
                and fused[-1].compute is op.compute):
            prev = fused[-1]

            def chained(block: Block, _prev=prev.fn, _next=op.fn) -> List[Block]:
                out: List[Block] = []
                for b in _prev(block):
                    out.extend(_next(b))
                return out

            fused[-1] = MapOp(name=f"{prev.name}->{op.name}", fn=chained,
                              compute=prev.compute)
        else:
            fused.append(op)
    return fused


# ---------------------------------------------------------------------------
# Streaming executor
# ---------------------------------------------------------------------------

def _apply_map(fn_blob_fn, block: Block) -> List[Block]:
    return fn_blob_fn(block)


class BackpressurePolicy:
    """Caps a map stage's concurrency (reference
    ``_internal/execution/backpressure_policy/`` role). Policies compose:
    the effective window is the MIN over all policies and the base
    ``max_in_flight``."""

    def max_in_flight(self, op: "MapOp", base: int) -> int:
        raise NotImplementedError


class ConcurrencyCapBackpressurePolicy(BackpressurePolicy):
    """Hard cap on concurrent tasks per stage (reference
    ``concurrency_cap_backpressure_policy.py``)."""

    def __init__(self, cap: int):
        self.cap = int(cap)

    def max_in_flight(self, op: "MapOp", base: int) -> int:
        return self.cap


class StoreMemoryBackpressurePolicy(BackpressurePolicy):
    """Shrinks the window while the local object store is above a
    utilization threshold — in-flight blocks pin store memory, so the
    stage must not outrun the consumer when the store is tight."""

    def __init__(self, threshold: float = 0.8, ttl_s: float = 0.5):
        self.threshold = threshold
        self.ttl_s = ttl_s
        self._cached = (0.0, 0.0)  # (monotonic ts, utilization)

    def _utilization(self) -> float:
        # store_bytes() scans /dev/shm — far too heavy for the per-dispatch
        # window check (object_store.py's own O(1)-per-put rule); sample it
        # on a short TTL instead
        import time as _time

        ts, util = self._cached
        now = _time.monotonic()
        if now - ts < self.ttl_s:
            return util
        util = 0.0
        try:
            # public API only (CLAUDE.md seam: ML libraries never touch
            # runtime/store internals)
            import ray_tpu

            mem = ray_tpu.object_store_memory()
            if mem["capacity_bytes"]:
                util = mem["used_bytes"] / mem["capacity_bytes"]
        except Exception:
            pass
        self._cached = (now, util)
        return util

    def max_in_flight(self, op: "MapOp", base: int) -> int:
        if self._utilization() > self.threshold:
            return max(1, base // 4)
        return base


@dataclass
class ExecutionOptions:
    max_in_flight: int = 8       # per map stage (backpressure window)
    preserve_order: bool = True
    # None -> the default rule-based optimizer (data/optimizer.py)
    optimizer: Optional[Any] = None
    backpressure_policies: Tuple[BackpressurePolicy, ...] = ()

    def effective_in_flight(self, op: "MapOp") -> int:
        out = self.max_in_flight
        for p in self.backpressure_policies:
            out = min(out, p.max_in_flight(op, self.max_in_flight))
        return max(1, out)


def execute_streaming(
    source: Iterator[Any],         # iterator of ObjectRef[Block] or Blocks
    ops: List[LogicalOp],
    options: Optional[ExecutionOptions] = None,
) -> Iterator[Any]:
    """Run the plan, yielding ObjectRefs of output blocks as they're ready.

    Consecutive (post-fusion) map operators — task OR actor-pool — run as
    ONE per-operator topology (:class:`TopologyExecutor`): each op keeps
    its own input/in-flight/output queues and a select-operator-to-run
    chooser advances whichever op has headroom, so a slow TPU-ingest
    stage and a fast CPU-decode stage genuinely overlap instead of the
    fast stage running ahead unboundedly or the chain serializing
    (reference ``streaming_executor_state.py:503``). All-to-all ops
    remain barriers between topology segments.
    """
    options = options or ExecutionOptions()
    if options.optimizer is None:
        from ray_tpu.data.optimizer import Optimizer

        ops = Optimizer().optimize(ops)
    else:
        ops = options.optimizer.optimize(ops)
    stream: Iterator[Any] = (_ensure_ref(x) for x in source)
    segment: List[MapOp] = []

    def flush_segment(stream, segment):
        if segment:
            stream = TopologyExecutor(stream, list(segment), options).run()
            segment.clear()
        return stream

    for op in ops:
        if isinstance(op, MapOp):
            segment.append(op)
        elif isinstance(op, ShuffleOp):
            stream = flush_segment(stream, segment)
            stream = _run_shuffle(stream, op)
        elif isinstance(op, AllToAllOp):
            stream = flush_segment(stream, segment)
            stream = _run_all_to_all(stream, op)
        elif isinstance(op, LimitOp):
            stream = flush_segment(stream, segment)
            stream = _run_limit(stream, op.limit)
        else:
            raise TypeError(f"unknown op {op!r}")
    return _unmark_on_yield(flush_segment(stream, segment))


def _unmark_on_yield(stream: Iterator[Any]) -> Iterator[Any]:
    """Refs escaping to the caller lose executor ownership: a later plan
    consuming them (e.g. sort over a materialized dataset) must never
    free the user's blocks."""
    for ref in stream:
        unmark_ephemeral(ref)
        yield ref


#: ids of refs OWNED by the executor (raw source blocks it put itself):
#: the streaming exchange may free these eagerly once consumed — user-held
#: refs are never marked, and refs yielded back to the caller are unmarked
#: first (see execute_streaming's final wrapper)
_EPHEMERAL: set = set()


def mark_ephemeral(ref) -> None:
    if len(_EPHEMERAL) > 100_000:
        # residue from abandoned plans (limit()/take() drop upstream
        # generators with marked refs in flight). Dropping marks is SAFE —
        # an unmarked block merely loses eager freeing and waits for
        # ObjectRef GC — so a rare wholesale clear bounds the set.
        _EPHEMERAL.clear()
    _EPHEMERAL.add(ref.id.binary())


def unmark_ephemeral(ref) -> None:
    _EPHEMERAL.discard(ref.id.binary())


def is_ephemeral(ref) -> bool:
    return ref.id.binary() in _EPHEMERAL


def _ensure_ref(x):
    if isinstance(x, ray_tpu.ObjectRef):
        return x
    ref = ray_tpu.put(x)
    # the caller handed a raw Block: the executor owns this ref and may
    # reclaim it the moment the plan consumed it
    mark_ephemeral(ref)
    return ref


# ---------------------------------------------------------------------------
# Per-operator streaming topology
# ---------------------------------------------------------------------------

class _TaskDispatcher:
    """Dispatch one streaming map task per block."""

    def __init__(self, op: MapOp):
        self._remote = ray_tpu.remote(num_returns="streaming")(
            lambda block, _fn=op.fn: iter(_fn(block)))

    def dispatch(self, ref):
        return self._remote.remote(ref)

    def task_finished(self, gen) -> None:
        pass

    def close(self) -> None:
        pass


class _ActorPoolDispatcher:
    """Reference ``ActorPoolMapOperator`` role: blocks run on warm actors
    (per-actor state loads once); the pool autoscales between min_size
    and max_size when every actor is saturated."""

    def __init__(self, op: MapOp):
        import cloudpickle as _cp

        self._strat = op.compute
        self._fn_blob = _cp.dumps(op.fn)
        self._actor_cls = ray_tpu.remote(_PoolActor)
        self._actors = [self._actor_cls.remote(self._fn_blob)
                        for _ in range(self._strat.min_size)]
        self._load: Dict[int, int] = {i: 0 for i in range(len(self._actors))}
        self._gen_actor: Dict[int, int] = {}  # id(gen) -> actor idx

    def dispatch(self, ref):
        idx = min(self._load, key=self._load.get)
        if (self._load[idx] >= self._strat.max_tasks_in_flight_per_actor
                and len(self._actors) < self._strat.max_size):
            self._actors.append(self._actor_cls.remote(self._fn_blob))
            idx = len(self._actors) - 1
            self._load[idx] = 0
        self._load[idx] += 1
        gen = self._actors[idx].apply.options(
            num_returns="streaming").remote(ref)
        self._gen_actor[id(gen)] = idx
        return gen

    def task_finished(self, gen) -> None:
        idx = self._gen_actor.pop(id(gen), None)
        if idx is not None:
            self._load[idx] -= 1

    def close(self) -> None:
        for a in self._actors:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass


class _InFlight:
    """One dispatched streaming task: its generator plus an ordered buffer
    of already-yielded (polled) output refs."""

    __slots__ = ("gen", "buf", "done")

    def __init__(self, gen):
        self.gen = gen
        self.buf: List[Any] = []
        self.done = False


class _OpState:
    """Per-operator queues (reference ``OpState`` in
    ``streaming_executor_state.py``): input refs waiting to dispatch,
    in-flight streaming tasks, and ready output refs."""

    def __init__(self, op: MapOp, options: ExecutionOptions):
        from collections import deque

        self.op = op
        self.options = options
        self._dispatcher = None  # LAZY: actor pools must not spawn until
        # the first block actually reaches this op (and never at all if
        # the plan iterator is dropped unconsumed)
        self.inq: "deque" = deque()
        self.inflight: List[_InFlight] = []
        self.outq: "deque" = deque()
        self.input_done = False
        self.max_inq_seen = 0

    @property
    def dispatcher(self):
        if self._dispatcher is None:
            self._dispatcher = (_ActorPoolDispatcher(self.op)
                                if self.op.compute is not None
                                else _TaskDispatcher(self.op))
        return self._dispatcher

    def close(self) -> None:
        if self._dispatcher is not None:
            self._dispatcher.close()

    # -- scheduling predicates -------------------------------------------

    def window(self) -> int:
        # static capacity math — must not instantiate the dispatcher
        win = self.options.effective_in_flight(self.op)
        strat = self.op.compute
        if strat is not None:
            win = min(win, max(1, strat.max_size
                               * strat.max_tasks_in_flight_per_actor))
        return win

    def can_dispatch(self) -> bool:
        return bool(self.inq) and len(self.inflight) < self.window()

    def dispatch_one(self) -> None:
        ref = self.inq.popleft()
        # consumed by a map stage: no exchange will ever see this ref, so
        # retire its ownership mark (keeps _EPHEMERAL from growing with
        # every intermediate block of map->map chains)
        unmark_ephemeral(ref)
        self.inflight.append(_InFlight(self.dispatcher.dispatch(ref)))

    def poll(self) -> int:
        """Drain ready items from every in-flight task into per-task
        buffers (non-blocking), then move buffered refs to ``outq`` —
        strictly FIFO across tasks when ``preserve_order`` (items within a
        task are ordered by the stream itself). Returns refs moved."""
        for f in self.inflight:
            while not f.done:
                try:
                    ref = f.gen.try_next()
                except StopIteration:
                    f.done = True
                    self.dispatcher.task_finished(f.gen)
                    break
                if ref is None:
                    break
                # map outputs are executor-owned until they escape to the
                # caller (execute_streaming unmarks at the final yield): a
                # downstream exchange may free them the moment they're
                # consumed
                mark_ephemeral(ref)
                f.buf.append(ref)
        moved = 0
        if self.options.preserve_order:
            while self.inflight:
                head = self.inflight[0]
                self.outq.extend(head.buf)
                moved += len(head.buf)
                head.buf.clear()
                if head.done:
                    self.inflight.pop(0)
                else:
                    break
        else:
            for f in list(self.inflight):
                self.outq.extend(f.buf)
                moved += len(f.buf)
                f.buf.clear()
                if f.done:
                    self.inflight.remove(f)
        return moved

    def exhausted(self) -> bool:
        return (self.input_done and not self.inq and not self.inflight
                and not self.outq)

    def watch_refs(self) -> List[Any]:
        """Refs to park on when the whole topology is idle: each live
        stream's next item + its completion sentinel."""
        out = []
        for f in self.inflight:
            if not f.done:
                out.append(f.gen.next_item_ref())
                out.append(f.gen.completed())
        return out


class TopologyExecutor:
    """select-operator-to-run loop over a chain of map operators
    (reference ``streaming_executor_state.py:503``).

    Every iteration: poll all streams (non-blocking), move outputs
    downstream under a bounded per-op input queue, then dispatch ONE task
    for the runnable op with the least buffered output — draining toward
    the consumer first keeps total buffered blocks bounded while letting
    fast and slow stages run concurrently. When nothing is runnable and
    nothing moved, park on the union of next-item/sentinel refs (no
    busy-wait, no per-stream blocking)."""

    def __init__(self, source: Iterator[Any], ops: List[MapOp],
                 options: ExecutionOptions):
        self.source = source
        self.options = options
        self.states = [_OpState(op, options) for op in ops]
        # bounded inter-op queue: a fast producer may run at most this far
        # ahead of its consumer (reference outqueue memory gating role)
        self.max_queued = max(2, 2 * options.max_in_flight)
        self.stats: Dict[str, Any] = {"max_inq": {}, "dispatches": {}}

    # -- plumbing ---------------------------------------------------------

    def _pull_source(self) -> None:
        first = self.states[0]
        while not first.input_done and len(first.inq) < self.max_queued:
            try:
                first.inq.append(next(self.source))
            except StopIteration:
                first.input_done = True
        first.max_inq_seen = max(first.max_inq_seen, len(first.inq))

    def _transfer(self) -> int:
        """outq[i] -> inq[i+1] under the bound; marks input_done edges."""
        moved = 0
        for i, st in enumerate(self.states[:-1]):
            nxt = self.states[i + 1]
            while st.outq and len(nxt.inq) < self.max_queued:
                nxt.inq.append(st.outq.popleft())
                moved += 1
            nxt.max_inq_seen = max(nxt.max_inq_seen, len(nxt.inq))
            if st.exhausted():
                nxt.input_done = True
        return moved

    def _select_op_to_run(self) -> Optional[_OpState]:
        """Runnable op with the least buffered output (its outq plus the
        downstream inq it feeds) — the reference's resource-aware choice,
        reduced to block counts."""
        best, best_score = None, None
        for i, st in enumerate(self.states):
            if not st.can_dispatch():
                continue
            downstream = (len(self.states[i + 1].inq)
                          if i + 1 < len(self.states) else 0)
            if i + 1 < len(self.states) and \
                    len(self.states[i + 1].inq) >= self.max_queued:
                continue  # downstream full: dispatching only buffers more
            score = len(st.outq) + downstream
            if best_score is None or score < best_score:
                best, best_score = st, score
        return best

    # -- main loop --------------------------------------------------------

    def run(self) -> Iterator[Any]:
        states = self.states
        last = states[-1]
        try:
            while True:
                self._pull_source()
                progressed = sum(st.poll() for st in states)
                progressed += self._transfer()
                st = self._select_op_to_run()
                if st is not None:
                    st.dispatch_one()
                    name = st.op.name
                    self.stats["dispatches"][name] = \
                        self.stats["dispatches"].get(name, 0) + 1
                    progressed += 1
                while last.outq:
                    yield last.outq.popleft()
                if all(s.exhausted() for s in states):
                    break
                if not progressed:
                    # idle: park until ANY stream produces or completes
                    watch = [r for s in states for r in s.watch_refs()]
                    if watch:
                        ray_tpu.wait(watch, num_returns=1, timeout=10)
        finally:
            for s in states:
                s.close()
            self.stats["max_inq"] = {s.op.name: s.max_inq_seen
                                     for s in states}
            _LAST_TOPOLOGY_STATS.clear()
            _LAST_TOPOLOGY_STATS.update(self.stats)


#: instrumentation for tests/debugging: queue-depth + dispatch counts of
#: the most recently finished topology segment
_LAST_TOPOLOGY_STATS: Dict[str, Any] = {}


def _run_all_to_all(stream: Iterator[Any], op: AllToAllOp) -> Iterator[Any]:
    blocks = []
    for r in stream:
        unmark_ephemeral(r)  # consumed here, never by an exchange
        blocks.append(ray_tpu.get(r))
    for out in op.fn(blocks):
        yield ray_tpu.put(out)


# ---------------------------------------------------------------------------
# Distributed shuffle (map/reduce exchange)
# ---------------------------------------------------------------------------

def _partition_rows(block: Block, assign: np.ndarray,
                    n_red: int) -> List[Block]:
    """Split ``block`` into ``n_red`` blocks by per-row reducer index."""
    out = []
    for j in range(n_red):
        idx = np.flatnonzero(assign == j)
        out.append({k: v[idx] for k, v in block.items()})
    return out


def _shuffle_partition(block: Block, n_red: int, kind: str, args: dict,
                       part_idx: int) -> List[Block]:
    n = block_num_rows(block)
    if kind == "random_shuffle":
        rng = np.random.default_rng(
            None if args.get("seed") is None
            else (int(args["seed"]) * 1000003 + part_idx))
        assign = rng.integers(0, n_red, size=n)
    elif kind == "sort":
        key = args["key"]
        bounds = np.asarray(args["boundaries"])
        assign = np.searchsorted(bounds, block[key], side="right")
        if args.get("descending"):
            assign = (n_red - 1) - assign
    elif kind == "repartition":
        # rows [global_start, global_start+n) cut into equal global ranges
        start = int(args["global_start"])
        size = max(1, int(args["target_size"]))
        assign = np.minimum((start + np.arange(n)) // size, n_red - 1)
    else:
        raise ValueError(kind)
    return _partition_rows(block, assign, n_red)


def _shuffle_reduce(kind: str, args: dict, red_idx: int,
                    *parts: Block) -> Block:
    merged = concat_blocks([p for p in parts if block_num_rows(p)])
    if not merged:
        return {}
    if kind == "random_shuffle":
        rng = np.random.default_rng(
            None if args.get("seed") is None
            else (int(args["seed"]) * 9176 + red_idx))
        perm = rng.permutation(block_num_rows(merged))
        return block_take(merged, perm)
    if kind == "sort":
        order = np.argsort(merged[args["key"]], kind="stable")
        if args.get("descending"):
            order = order[::-1]
        return block_take(merged, order)
    return merged  # repartition: concat is the whole job


def _run_shuffle(stream: Iterator[Any], op: ShuffleOp) -> Iterator[Any]:
    """Distributed exchange. Default: the streaming engine
    (``data/streaming.py``) — bounded blocks-in-flight, reducer actors,
    spill-absorbed memory pressure, no global barrier. The legacy one-shot
    task exchange below remains behind ``RTPU_DATA_STREAMING_EXCHANGE=0``."""
    from ray_tpu import config as _config

    if _config.get("data_streaming_exchange"):
        from ray_tpu.data.streaming import run_exchange

        return run_exchange(op.kind, dict(op.args), stream)
    return _run_shuffle_tasks(stream, op)


def _run_shuffle_tasks(stream: Iterator[Any], op: ShuffleOp) -> Iterator[Any]:
    """Legacy task-based exchange (reference all-to-all ops,
    ``_internal/planner/exchange/``): a barrier on block REFS only — the
    driver orchestrates tasks and never materializes block bytes
    (VERDICT r3 #5; the old path pulled the whole dataset into the
    driver) — but every partition block exists in the store at once, so
    it cannot exceed store+spill capacity headroom the way the streaming
    engine can."""
    refs = list(stream)
    for r in refs:
        unmark_ephemeral(r)  # consumed here; this path never frees
    if not refs:
        return
    args = dict(op.args)
    n_red = int(args.get("num_blocks") or len(refs))

    if op.kind == "sort":
        args.update(sample_sort_boundaries(refs, args["key"],
                                           bool(args.get("descending")),
                                           n_red))
    elif op.kind == "repartition":
        args["target_size"], offsets = repartition_layout(refs, n_red)

    if n_red > 1:
        part_task = ray_tpu.remote(num_returns=n_red)(_shuffle_partition)
    else:
        # single reducer: unwrap the 1-element list in the task itself
        part_task = ray_tpu.remote(
            lambda r, n, k, a, i: _shuffle_partition(r, n, k, a, i)[0])
    parts: List[List[Any]] = []
    for i, r in enumerate(refs):
        a = dict(args)
        if op.kind == "repartition":
            a["global_start"] = int(offsets[i])
        out = part_task.remote(r, n_red, op.kind, a, i)
        parts.append(out if n_red > 1 else [out])

    reduce_task = ray_tpu.remote(_shuffle_reduce)
    for j in range(n_red):
        yield reduce_task.remote(op.kind, args, j,
                                 *[parts[i][j] for i in range(len(parts))])


def sample_sort_boundaries(refs: List[Any], key: str, descending: bool,
                           n_red: int) -> Dict[str, Any]:
    """Sample per-block quantiles and derive reducer key boundaries
    (index-based selection, not np.quantile: works for any sortable
    dtype, strings included). Barrier on refs only; shared by the
    streaming engine and the legacy exchange so the two paths can never
    diverge on boundary math."""
    @ray_tpu.remote
    def _sample(block, k=key):
        vals = block[k]
        if len(vals) == 0:
            return np.asarray([])
        take = min(32, len(vals))
        idx = np.linspace(0, len(vals) - 1, take).astype(np.int64)
        return np.sort(vals)[idx]

    samples = np.concatenate(
        [np.asarray(s) for s in
         ray_tpu.get([_sample.remote(r) for r in refs])] or
        [np.asarray([])])
    if len(samples) == 0:
        bounds = np.asarray([])
    else:
        ss = np.sort(samples)
        idxs = (np.linspace(0, 1, n_red + 1)[1:-1]
                * (len(ss) - 1)).astype(np.int64)
        bounds = ss[idxs]
    return {"boundaries": bounds, "descending": descending}


def repartition_layout(refs: List[Any], n_red: int):
    """(target_size, per-block global row offsets) for an equal-range
    repartition — shared by both exchange paths."""
    @ray_tpu.remote
    def _count(block):
        return block_num_rows(block)

    counts = ray_tpu.get([_count.remote(r) for r in refs])
    total = int(sum(counts))
    target_size = max(1, (total + n_red - 1) // n_red)
    offsets = (list(np.concatenate(
        [[0], np.cumsum(counts)[:-1]]).astype(np.int64))
        if counts else [])
    return target_size, offsets


# ---------------------------------------------------------------------------
# Actor-pool map stage
# ---------------------------------------------------------------------------

class _PoolActor:
    """One warm actor of an actor-pool map stage."""

    def __init__(self, fn_blob: bytes):
        import cloudpickle as _cp

        self._fn = _cp.loads(fn_blob)

    def apply(self, block):
        for out in self._fn(block):
            yield out


def _run_limit(stream: Iterator[Any], limit: int) -> Iterator[Any]:
    remaining = limit
    for ref in stream:
        if remaining <= 0:
            return
        block = ray_tpu.get(ref)
        n = block_num_rows(block)
        if n <= remaining:
            remaining -= n
            yield ref
        else:
            yield ray_tpu.put(block_slice(block, 0, remaining))
            remaining = 0
            return


