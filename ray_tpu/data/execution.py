"""Streaming execution of logical plans over the task runtime.

Role analog: ``python/ray/data/_internal/execution/streaming_executor.py:48``
and its Topology loop (``streaming_executor_state.py``). Same ideas, compact
form: logical map-ish operators are **fused** into one task per block
(reference optimizer's fusion rule), blocks flow through the fused pipeline
as object refs with a bounded number of in-flight tasks (backpressure), and
all-to-all ops (shuffle/sort/repartition/groupby) are barriers that
materialize their input refs.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

import ray_tpu
from ray_tpu.data.block import (
    Block,
    block_num_rows,
    block_slice,
    block_take,
    concat_blocks,
)


# ---------------------------------------------------------------------------
# Logical operators
# ---------------------------------------------------------------------------

@dataclass
class MapOp:
    """Block -> List[Block] transform; fusible with neighbors."""

    name: str
    fn: Callable[[Block], List[Block]]


@dataclass
class AllToAllOp:
    """Barrier op consuming all blocks at once."""

    name: str
    fn: Callable[[List[Block]], List[Block]]


@dataclass
class LimitOp:
    name: str
    limit: int


LogicalOp = Any  # MapOp | AllToAllOp | LimitOp


def fuse_ops(ops: List[LogicalOp]) -> List[LogicalOp]:
    """Merge consecutive MapOps into single fused stages (the reference's
    OperatorFusionRule): one task per block runs the whole chain."""
    fused: List[LogicalOp] = []
    for op in ops:
        if isinstance(op, MapOp) and fused and isinstance(fused[-1], MapOp):
            prev = fused[-1]

            def chained(block: Block, _prev=prev.fn, _next=op.fn) -> List[Block]:
                out: List[Block] = []
                for b in _prev(block):
                    out.extend(_next(b))
                return out

            fused[-1] = MapOp(name=f"{prev.name}->{op.name}", fn=chained)
        else:
            fused.append(op)
    return fused


# ---------------------------------------------------------------------------
# Streaming executor
# ---------------------------------------------------------------------------

def _apply_map(fn_blob_fn, block: Block) -> List[Block]:
    return fn_blob_fn(block)


@dataclass
class ExecutionOptions:
    max_in_flight: int = 8       # per map stage (backpressure window)
    preserve_order: bool = True


def execute_streaming(
    source: Iterator[Any],         # iterator of ObjectRef[Block] or Blocks
    ops: List[LogicalOp],
    options: Optional[ExecutionOptions] = None,
) -> Iterator[Any]:
    """Run the plan, yielding ObjectRefs of output blocks as they're ready."""
    options = options or ExecutionOptions()
    ops = fuse_ops(ops)
    stream: Iterator[Any] = (_ensure_ref(x) for x in source)
    for op in ops:
        if isinstance(op, MapOp):
            stream = _run_map_stage(stream, op, options)
        elif isinstance(op, AllToAllOp):
            stream = _run_all_to_all(stream, op)
        elif isinstance(op, LimitOp):
            stream = _run_limit(stream, op.limit)
        else:
            raise TypeError(f"unknown op {op!r}")
    return stream


def _ensure_ref(x):
    from ray_tpu.core.object_ref import ObjectRef

    if isinstance(x, ObjectRef):
        return x
    return ray_tpu.put(x)


def _run_map_stage(stream: Iterator[Any], op: MapOp,
                   options: ExecutionOptions) -> Iterator[Any]:
    """Bounded-in-flight task pool over input refs (streaming backpressure:
    reference ``select_operator_to_run``'s resource gating, reduced to a
    window of ``max_in_flight`` concurrent tasks).

    Each map task is a STREAMING task: output blocks surface as refs the
    moment the worker yields them (overlapping producer/consumer, the
    reference's streaming-exchange behavior) and block bytes never round-
    trip through the driver."""
    remote_fn = ray_tpu.remote(num_returns="streaming")(
        lambda block, _fn=op.fn: iter(_fn(block)))
    in_flight: List[Any] = []

    for ref in stream:
        in_flight.append(remote_fn.remote(ref))
        while len(in_flight) >= options.max_in_flight:
            yield from in_flight.pop(0)
    for gen in in_flight:
        yield from gen


def _run_all_to_all(stream: Iterator[Any], op: AllToAllOp) -> Iterator[Any]:
    blocks = [ray_tpu.get(r) for r in stream]
    for out in op.fn(blocks):
        yield ray_tpu.put(out)


def _run_limit(stream: Iterator[Any], limit: int) -> Iterator[Any]:
    remaining = limit
    for ref in stream:
        if remaining <= 0:
            return
        block = ray_tpu.get(ref)
        n = block_num_rows(block)
        if n <= remaining:
            remaining -= n
            yield ref
        else:
            yield ray_tpu.put(block_slice(block, 0, remaining))
            remaining = 0
            return


# ---------------------------------------------------------------------------
# All-to-all implementations
# ---------------------------------------------------------------------------

def shuffle_fn(seed: Optional[int]) -> Callable[[List[Block]], List[Block]]:
    def _shuffle(blocks: List[Block]) -> List[Block]:
        whole = concat_blocks(blocks)
        n = block_num_rows(whole)
        rng = np.random.default_rng(seed)
        perm = rng.permutation(n)
        shuffled = block_take(whole, perm)
        # keep roughly the original partitioning
        k = max(len(blocks), 1)
        size = max(1, (n + k - 1) // k)
        return [block_slice(shuffled, i, min(i + size, n))
                for i in range(0, n, size)]

    return _shuffle


def repartition_fn(num_blocks: int) -> Callable[[List[Block]], List[Block]]:
    def _repartition(blocks: List[Block]) -> List[Block]:
        whole = concat_blocks(blocks)
        n = block_num_rows(whole)
        if n == 0:
            return []
        size = max(1, (n + num_blocks - 1) // num_blocks)
        return [block_slice(whole, i, min(i + size, n))
                for i in range(0, n, size)]

    return _repartition


def sort_fn(key: str, descending: bool = False
            ) -> Callable[[List[Block]], List[Block]]:
    def _sort(blocks: List[Block]) -> List[Block]:
        whole = concat_blocks(blocks)
        if not whole:
            return []
        order = np.argsort(whole[key], kind="stable")
        if descending:
            order = order[::-1]
        out = block_take(whole, order)
        k = max(len(blocks), 1)
        n = block_num_rows(out)
        size = max(1, (n + k - 1) // k)
        return [block_slice(out, i, min(i + size, n))
                for i in range(0, n, size)]

    return _sort
