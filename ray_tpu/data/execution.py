"""Streaming execution of logical plans over the task runtime.

Role analog: ``python/ray/data/_internal/execution/streaming_executor.py:48``
and its Topology loop (``streaming_executor_state.py``). Same ideas, compact
form: logical map-ish operators are **fused** into one task per block
(reference optimizer's fusion rule), blocks flow through the fused pipeline
as object refs with a bounded number of in-flight tasks (backpressure), and
all-to-all ops (shuffle/sort/repartition/groupby) are barriers that
materialize their input refs.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

import ray_tpu
from ray_tpu.data.block import (
    Block,
    block_num_rows,
    block_slice,
    block_take,
    concat_blocks,
)


# ---------------------------------------------------------------------------
# Logical operators
# ---------------------------------------------------------------------------

@dataclass
class MapOp:
    """Block -> List[Block] transform; fusible with neighbors.

    ``compute``: None runs each block as a task; an ActorPoolStrategy
    runs blocks on a warm autoscaling actor pool (expensive per-block
    setup like model weights loads once per actor)."""

    name: str
    fn: Callable[[Block], List[Block]]
    compute: Optional["ActorPoolStrategy"] = None


@dataclass
class ActorPoolStrategy:
    """Reference ``ActorPoolMapOperator`` role: min_size warm actors,
    growing to max_size while the input queue is deep."""

    min_size: int = 1
    max_size: int = 4
    max_tasks_in_flight_per_actor: int = 2


@dataclass
class AllToAllOp:
    """Barrier op consuming all blocks at once (driver-side; only for
    custom user fns — the built-in exchanges use ShuffleOp)."""

    name: str
    fn: Callable[[List[Block]], List[Block]]


@dataclass
class ShuffleOp:
    """Distributed all-to-all (reference exchange ops under
    ``data/_internal/planner/exchange/``): partition tasks emit one block
    per reducer, reduce tasks consume the refs — block bytes NEVER pass
    through the driver. ``kind``: random_shuffle | repartition | sort."""

    name: str
    kind: str
    args: Dict[str, Any] = field(default_factory=dict)


@dataclass
class LimitOp:
    name: str
    limit: int


LogicalOp = Any  # MapOp | AllToAllOp | ShuffleOp | LimitOp


def fuse_ops(ops: List[LogicalOp]) -> List[LogicalOp]:
    """Merge consecutive MapOps into single fused stages (the reference's
    OperatorFusionRule): one task per block runs the whole chain."""
    fused: List[LogicalOp] = []
    for op in ops:
        if (isinstance(op, MapOp) and fused
                and isinstance(fused[-1], MapOp)
                and fused[-1].compute is op.compute):
            prev = fused[-1]

            def chained(block: Block, _prev=prev.fn, _next=op.fn) -> List[Block]:
                out: List[Block] = []
                for b in _prev(block):
                    out.extend(_next(b))
                return out

            fused[-1] = MapOp(name=f"{prev.name}->{op.name}", fn=chained,
                              compute=prev.compute)
        else:
            fused.append(op)
    return fused


# ---------------------------------------------------------------------------
# Streaming executor
# ---------------------------------------------------------------------------

def _apply_map(fn_blob_fn, block: Block) -> List[Block]:
    return fn_blob_fn(block)


class BackpressurePolicy:
    """Caps a map stage's concurrency (reference
    ``_internal/execution/backpressure_policy/`` role). Policies compose:
    the effective window is the MIN over all policies and the base
    ``max_in_flight``."""

    def max_in_flight(self, op: "MapOp", base: int) -> int:
        raise NotImplementedError


class ConcurrencyCapBackpressurePolicy(BackpressurePolicy):
    """Hard cap on concurrent tasks per stage (reference
    ``concurrency_cap_backpressure_policy.py``)."""

    def __init__(self, cap: int):
        self.cap = int(cap)

    def max_in_flight(self, op: "MapOp", base: int) -> int:
        return self.cap


class StoreMemoryBackpressurePolicy(BackpressurePolicy):
    """Shrinks the window while the local object store is above a
    utilization threshold — in-flight blocks pin store memory, so the
    stage must not outrun the consumer when the store is tight."""

    def __init__(self, threshold: float = 0.8, ttl_s: float = 0.5):
        self.threshold = threshold
        self.ttl_s = ttl_s
        self._cached = (0.0, 0.0)  # (monotonic ts, utilization)

    def _utilization(self) -> float:
        # store_bytes() scans /dev/shm — far too heavy for the per-dispatch
        # window check (object_store.py's own O(1)-per-put rule); sample it
        # on a short TTL instead
        import time as _time

        ts, util = self._cached
        now = _time.monotonic()
        if now - ts < self.ttl_s:
            return util
        util = 0.0
        try:
            # public API only (CLAUDE.md seam: ML libraries never touch
            # runtime/store internals)
            import ray_tpu

            mem = ray_tpu.object_store_memory()
            if mem["capacity_bytes"]:
                util = mem["used_bytes"] / mem["capacity_bytes"]
        except Exception:
            pass
        self._cached = (now, util)
        return util

    def max_in_flight(self, op: "MapOp", base: int) -> int:
        if self._utilization() > self.threshold:
            return max(1, base // 4)
        return base


@dataclass
class ExecutionOptions:
    max_in_flight: int = 8       # per map stage (backpressure window)
    preserve_order: bool = True
    # None -> the default rule-based optimizer (data/optimizer.py)
    optimizer: Optional[Any] = None
    backpressure_policies: Tuple[BackpressurePolicy, ...] = ()

    def effective_in_flight(self, op: "MapOp") -> int:
        out = self.max_in_flight
        for p in self.backpressure_policies:
            out = min(out, p.max_in_flight(op, self.max_in_flight))
        return max(1, out)


def execute_streaming(
    source: Iterator[Any],         # iterator of ObjectRef[Block] or Blocks
    ops: List[LogicalOp],
    options: Optional[ExecutionOptions] = None,
) -> Iterator[Any]:
    """Run the plan, yielding ObjectRefs of output blocks as they're ready."""
    options = options or ExecutionOptions()
    if options.optimizer is None:
        from ray_tpu.data.optimizer import Optimizer

        ops = Optimizer().optimize(ops)
    else:
        ops = options.optimizer.optimize(ops)
    stream: Iterator[Any] = (_ensure_ref(x) for x in source)
    for op in ops:
        if isinstance(op, MapOp):
            if op.compute is not None:
                stream = _run_actor_map_stage(stream, op, options)
            else:
                stream = _run_map_stage(stream, op, options)
        elif isinstance(op, ShuffleOp):
            stream = _run_shuffle(stream, op)
        elif isinstance(op, AllToAllOp):
            stream = _run_all_to_all(stream, op)
        elif isinstance(op, LimitOp):
            stream = _run_limit(stream, op.limit)
        else:
            raise TypeError(f"unknown op {op!r}")
    return stream


def _ensure_ref(x):
    from ray_tpu.core.object_ref import ObjectRef

    if isinstance(x, ObjectRef):
        return x
    return ray_tpu.put(x)


def _run_map_stage(stream: Iterator[Any], op: MapOp,
                   options: ExecutionOptions) -> Iterator[Any]:
    """Bounded-in-flight task pool over input refs (streaming backpressure:
    reference ``select_operator_to_run``'s resource gating, reduced to a
    window of ``max_in_flight`` concurrent tasks).

    Each map task is a STREAMING task: output blocks surface as refs the
    moment the worker yields them (overlapping producer/consumer, the
    reference's streaming-exchange behavior) and block bytes never round-
    trip through the driver."""
    remote_fn = ray_tpu.remote(num_returns="streaming")(
        lambda block, _fn=op.fn: iter(_fn(block)))
    in_flight: List[Any] = []

    for ref in stream:
        in_flight.append(remote_fn.remote(ref))
        # the window is re-evaluated per dispatch: memory-aware policies
        # tighten it dynamically (reference backpressure_policy loop)
        while len(in_flight) >= options.effective_in_flight(op):
            yield from in_flight.pop(0)
    for gen in in_flight:
        yield from gen


def _run_all_to_all(stream: Iterator[Any], op: AllToAllOp) -> Iterator[Any]:
    blocks = [ray_tpu.get(r) for r in stream]
    for out in op.fn(blocks):
        yield ray_tpu.put(out)


# ---------------------------------------------------------------------------
# Distributed shuffle (map/reduce exchange)
# ---------------------------------------------------------------------------

def _partition_rows(block: Block, assign: np.ndarray,
                    n_red: int) -> List[Block]:
    """Split ``block`` into ``n_red`` blocks by per-row reducer index."""
    out = []
    for j in range(n_red):
        idx = np.flatnonzero(assign == j)
        out.append({k: v[idx] for k, v in block.items()})
    return out


def _shuffle_partition(block: Block, n_red: int, kind: str, args: dict,
                       part_idx: int) -> List[Block]:
    n = block_num_rows(block)
    if kind == "random_shuffle":
        rng = np.random.default_rng(
            None if args.get("seed") is None
            else (int(args["seed"]) * 1000003 + part_idx))
        assign = rng.integers(0, n_red, size=n)
    elif kind == "sort":
        key = args["key"]
        bounds = np.asarray(args["boundaries"])
        assign = np.searchsorted(bounds, block[key], side="right")
        if args.get("descending"):
            assign = (n_red - 1) - assign
    elif kind == "repartition":
        # rows [global_start, global_start+n) cut into equal global ranges
        start = int(args["global_start"])
        size = max(1, int(args["target_size"]))
        assign = np.minimum((start + np.arange(n)) // size, n_red - 1)
    else:
        raise ValueError(kind)
    return _partition_rows(block, assign, n_red)


def _shuffle_reduce(kind: str, args: dict, red_idx: int,
                    *parts: Block) -> Block:
    merged = concat_blocks([p for p in parts if block_num_rows(p)])
    if not merged:
        return {}
    if kind == "random_shuffle":
        rng = np.random.default_rng(
            None if args.get("seed") is None
            else (int(args["seed"]) * 9176 + red_idx))
        perm = rng.permutation(block_num_rows(merged))
        return block_take(merged, perm)
    if kind == "sort":
        order = np.argsort(merged[args["key"]], kind="stable")
        if args.get("descending"):
            order = order[::-1]
        return block_take(merged, order)
    return merged  # repartition: concat is the whole job


def _run_shuffle(stream: Iterator[Any], op: ShuffleOp) -> Iterator[Any]:
    """Task-based exchange (reference all-to-all ops,
    ``_internal/planner/exchange/``): a barrier on block REFS only — the
    driver orchestrates tasks and never materializes block bytes
    (VERDICT r3 #5; the old path pulled the whole dataset into the
    driver)."""
    refs = list(stream)
    if not refs:
        return
    args = dict(op.args)
    n_red = int(args.get("num_blocks") or len(refs))

    if op.kind == "sort":
        key, desc = args["key"], bool(args.get("descending"))

        @ray_tpu.remote
        def _sample(block, k=key):
            vals = block[k]
            if len(vals) == 0:
                return np.asarray([])
            take = min(32, len(vals))
            idx = np.linspace(0, len(vals) - 1, take).astype(np.int64)
            return np.sort(vals)[idx]

        samples = np.concatenate(
            [np.asarray(s) for s in
             ray_tpu.get([_sample.remote(r) for r in refs])] or
            [np.asarray([])])
        if len(samples) == 0:
            bounds = np.asarray([])
        else:
            # index-based boundary selection (not np.quantile): works for
            # any sortable dtype, strings included
            ss = np.sort(samples)
            idxs = (np.linspace(0, 1, n_red + 1)[1:-1]
                    * (len(ss) - 1)).astype(np.int64)
            bounds = ss[idxs]
        args["boundaries"] = bounds
        args["descending"] = desc
    elif op.kind == "repartition":
        @ray_tpu.remote
        def _count(block):
            return block_num_rows(block)

        counts = ray_tpu.get([_count.remote(r) for r in refs])
        total = int(sum(counts))
        args["target_size"] = max(1, (total + n_red - 1) // n_red)
        offsets = np.concatenate([[0], np.cumsum(counts)[:-1]])

    if n_red > 1:
        part_task = ray_tpu.remote(num_returns=n_red)(_shuffle_partition)
    else:
        # single reducer: unwrap the 1-element list in the task itself
        part_task = ray_tpu.remote(
            lambda r, n, k, a, i: _shuffle_partition(r, n, k, a, i)[0])
    parts: List[List[Any]] = []
    for i, r in enumerate(refs):
        a = dict(args)
        if op.kind == "repartition":
            a["global_start"] = int(offsets[i])
        out = part_task.remote(r, n_red, op.kind, a, i)
        parts.append(out if n_red > 1 else [out])

    reduce_task = ray_tpu.remote(_shuffle_reduce)
    for j in range(n_red):
        yield reduce_task.remote(op.kind, args, j,
                                 *[parts[i][j] for i in range(len(parts))])


# ---------------------------------------------------------------------------
# Actor-pool map stage
# ---------------------------------------------------------------------------

class _PoolActor:
    """One warm actor of an actor-pool map stage."""

    def __init__(self, fn_blob: bytes):
        import cloudpickle as _cp

        self._fn = _cp.loads(fn_blob)

    def apply(self, block):
        for out in self._fn(block):
            yield out


def _run_actor_map_stage(stream: Iterator[Any], op: MapOp,
                         options: ExecutionOptions) -> Iterator[Any]:
    """Reference ``ActorPoolMapOperator`` role: blocks run on warm actors
    (per-actor state loads once), the pool autoscales between min_size and
    max_size on queue depth, and outputs stream as refs."""
    import cloudpickle as _cp

    strat = op.compute
    fn_blob = _cp.dumps(op.fn)
    actor_cls = ray_tpu.remote(_PoolActor)
    actors = [actor_cls.remote(fn_blob) for _ in range(strat.min_size)]
    load: Dict[int, int] = {i: 0 for i in range(len(actors))}
    in_flight: List[Tuple[int, Any]] = []  # (actor idx, generator)

    def dispatch(ref):
        # least-loaded actor; grow the pool when everyone is saturated
        idx = min(load, key=load.get)
        if (load[idx] >= strat.max_tasks_in_flight_per_actor
                and len(actors) < strat.max_size):
            actors.append(actor_cls.remote(fn_blob))
            idx = len(actors) - 1
            load[idx] = 0
        load[idx] += 1
        gen = actors[idx].apply.options(
            num_returns="streaming").remote(ref)
        in_flight.append((idx, gen))

    pool_cap = max(1, strat.max_size * strat.max_tasks_in_flight_per_actor)
    try:
        for ref in stream:
            dispatch(ref)
            # backpressure policies bound actor stages too (same MIN
            # contract as task stages); re-evaluated per dispatch
            cap = min(pool_cap, options.effective_in_flight(op))
            while len(in_flight) >= cap:
                idx, gen = in_flight.pop(0)
                yield from gen
                load[idx] -= 1
        for idx, gen in in_flight:
            yield from gen
            load[idx] -= 1
    finally:
        # an early-stopping consumer (take()/limit()) closes this
        # generator mid-stream: the pool must not outlive the stage
        for a in actors:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass


def _run_limit(stream: Iterator[Any], limit: int) -> Iterator[Any]:
    remaining = limit
    for ref in stream:
        if remaining <= 0:
            return
        block = ray_tpu.get(ref)
        n = block_num_rows(block)
        if n <= remaining:
            remaining -= n
            yield ref
        else:
            yield ray_tpu.put(block_slice(block, 0, remaining))
            remaining = 0
            return


