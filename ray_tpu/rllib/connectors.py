"""ConnectorV2: env->module and module->env transform pipelines.

Role analog: ``rllib/connectors/connector_v2.py`` — composable, stateful
transforms between environment data and module inputs/outputs. The env
runner applies the env-to-module pipeline to observations before the
forward pass and the module-to-env pipeline to actions before stepping.
"""

from __future__ import annotations

from typing import Any, List, Optional

import numpy as np


class ConnectorV2:
    """One transform stage. Override ``__call__(data) -> data``; stateful
    connectors (normalizers) keep running statistics and expose
    get_state/set_state for checkpoint/restore."""

    def __call__(self, data: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def get_state(self) -> Any:
        return None

    def set_state(self, state: Any) -> None:
        pass


class ConnectorPipelineV2(ConnectorV2):
    def __init__(self, connectors: Optional[List[ConnectorV2]] = None):
        self.connectors = list(connectors or [])

    def append(self, connector: ConnectorV2) -> "ConnectorPipelineV2":
        self.connectors.append(connector)
        return self

    def __call__(self, data, **kwargs):
        for c in self.connectors:
            try:
                data = c(data, **kwargs)
            except TypeError:
                data = c(data)  # stateless connector without the kwarg
        return data

    def get_state(self):
        return [c.get_state() for c in self.connectors]

    def set_state(self, state):
        for c, s in zip(self.connectors, state):
            c.set_state(s)

    def __len__(self):
        return len(self.connectors)


class FlattenObservations(ConnectorV2):
    """[N, ...] -> [N, prod(...)] (reference flatten_observations)."""

    def __call__(self, obs):
        return np.asarray(obs).reshape(len(obs), -1)


class NormalizeObservations(ConnectorV2):
    """Running mean/std normalization (reference MeanStdFilter role).

    Batched Chan parallel-variance update: O(1) numpy ops per call on the
    sampling hot path, same running statistics as per-row Welford.
    ``update=False`` applies the current statistics without absorbing the
    batch (boundary observations that the next fragment re-feeds would
    otherwise be counted twice).
    """

    def __init__(self, epsilon: float = 1e-8, clip: float = 10.0):
        self.eps = epsilon
        self.clip = clip
        self._count = 0.0
        self._mean: Optional[np.ndarray] = None
        self._m2: Optional[np.ndarray] = None

    def __call__(self, obs, update: bool = True):
        obs = np.asarray(obs, np.float64)
        if self._mean is None:
            self._mean = np.zeros(obs.shape[1:], np.float64)
            self._m2 = np.zeros(obs.shape[1:], np.float64)
        if update and len(obs):
            b_n = float(len(obs))
            b_mean = obs.mean(axis=0)
            b_m2 = ((obs - b_mean) ** 2).sum(axis=0)
            delta = b_mean - self._mean
            total = self._count + b_n
            self._mean += delta * (b_n / total)
            self._m2 += b_m2 + delta ** 2 * (self._count * b_n / total)
            self._count = total
        var = self._m2 / max(self._count - 1.0, 1.0)
        out = (obs - self._mean) / np.sqrt(var + self.eps)
        return np.clip(out, -self.clip, self.clip).astype(np.float32)

    def get_state(self):
        return (self._count, None if self._mean is None else self._mean.copy(),
                None if self._m2 is None else self._m2.copy())

    def set_state(self, state):
        self._count, self._mean, self._m2 = state


class ClipActions(ConnectorV2):
    """module->env: clip continuous actions into the env's bounds."""

    def __init__(self, low, high):
        self.low = np.asarray(low)
        self.high = np.asarray(high)

    def __call__(self, actions):
        return np.clip(actions, self.low, self.high)


class ScaleActions(ConnectorV2):
    """module->env: affine map from [-1, 1] (tanh policies) to [low, high]."""

    def __init__(self, low, high):
        self.low = np.asarray(low, np.float32)
        self.high = np.asarray(high, np.float32)

    def __call__(self, actions):
        return self.low + (np.asarray(actions) + 1.0) * 0.5 * (
            self.high - self.low)
