"""AlgorithmConfig + Algorithm: the RL training driver.

Role analog: ``rllib/algorithms/algorithm.py:213`` (a Tune Trainable whose
``step`` runs ``training_step``) and the fluent ``AlgorithmConfig``
(``algorithm_config.py``). EnvRunnerGroup fans out sampling to CPU actors
via the fault-tolerant manager; the learner group updates on device.
"""

from __future__ import annotations

import copy
from typing import Any, Callable, Dict, List, Optional, Type

import numpy as np

from ray_tpu.rllib.actor_manager import FaultTolerantActorManager
from ray_tpu.rllib.env_runner import SingleAgentEnvRunner
from ray_tpu.tune.trainable import Trainable


class AlgorithmConfig:
    """Fluent builder (reference ``AlgorithmConfig``): ``.environment()``,
    ``.env_runners()``, ``.training()``, ``.build()``."""

    def __init__(self, algo_class: Optional[Type["Algorithm"]] = None):
        self.algo_class = algo_class
        self.env: Optional[str] = None
        self.env_config: Dict[str, Any] = {}
        self.num_env_runners = 0           # 0 => local runner in-process
        self.num_envs_per_env_runner = 1
        self.rollout_fragment_length = 200
        self.num_learners = 0              # 0 => local learner
        self.lr = 3e-4
        self.gamma = 0.99
        self.train_batch_size = 4000
        self.minibatch_size = 128
        self.num_epochs = 4
        self.grad_clip = 0.5
        self.seed = 0
        self.extra: Dict[str, Any] = {}

    # -- fluent setters ---------------------------------------------------

    def environment(self, env: str, *, env_config: Optional[Dict] = None
                    ) -> "AlgorithmConfig":
        self.env = env
        if env_config:
            self.env_config = env_config
        return self

    def env_runners(self, *, num_env_runners: Optional[int] = None,
                    num_envs_per_env_runner: Optional[int] = None,
                    rollout_fragment_length: Optional[int] = None
                    ) -> "AlgorithmConfig":
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if num_envs_per_env_runner is not None:
            self.num_envs_per_env_runner = num_envs_per_env_runner
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def learners(self, *, num_learners: Optional[int] = None
                 ) -> "AlgorithmConfig":
        if num_learners is not None:
            self.num_learners = num_learners
        return self

    def training(self, **kwargs) -> "AlgorithmConfig":
        for k, v in kwargs.items():
            if hasattr(self, k):
                setattr(self, k, v)
            else:
                self.extra[k] = v
        return self

    def debugging(self, *, seed: Optional[int] = None) -> "AlgorithmConfig":
        if seed is not None:
            self.seed = seed
        return self

    def copy(self) -> "AlgorithmConfig":
        return copy.deepcopy(self)

    def update_from_dict(self, d: Dict[str, Any]) -> "AlgorithmConfig":
        for k, v in d.items():
            if hasattr(self, k):
                setattr(self, k, v)
            else:
                self.extra[k] = v
        return self

    def to_dict(self) -> Dict[str, Any]:
        d = {k: v for k, v in vars(self).items()
             if k not in ("algo_class",) and not k.startswith("_")}
        return d

    def build(self) -> "Algorithm":
        assert self.algo_class is not None, "config has no algorithm class"
        return self.algo_class(self)


class Algorithm(Trainable):
    """Base RL algorithm; subclasses override ``training_step``."""

    config_cls = AlgorithmConfig

    @classmethod
    def get_default_config(cls) -> AlgorithmConfig:
        return cls.config_cls(cls)

    def __init__(self, config, trial_dir: str = "."):
        # Tune passes a plain dict (trial actor construction); standalone
        # use passes an AlgorithmConfig.
        if isinstance(config, dict):
            config = self.get_default_config().update_from_dict(config)
        self.algo_config = config
        super().__init__(config.to_dict(), trial_dir)
        self._setup_algo()
        self._setup_done = True

    # Trainable.setup is a no-op; Algorithm wires itself in __init__ so it
    # can also be used standalone (algo = config.build(); algo.train()).
    def setup(self, config):
        pass

    def _setup_algo(self):
        cfg = self.algo_config
        # Probe the env once to derive the module spec.
        probe = SingleAgentEnvRunner(cfg.env, 1, None, cfg.seed,
                                     cfg.env_config)
        self.module_spec = self._transform_module_spec(probe.get_spec())
        probe.stop()

        if cfg.num_env_runners > 0:
            import ray_tpu

            runner_cls = ray_tpu.remote(SingleAgentEnvRunner)

            def make_runner(i: int):
                return runner_cls.options(num_cpus=1).remote(
                    cfg.env, cfg.num_envs_per_env_runner, self.module_spec,
                    cfg.seed + i * 1000 + 1, cfg.env_config)

            self.env_runner_group = FaultTolerantActorManager(
                make_runner, cfg.num_env_runners)
            self.local_runner = None
        else:
            self.env_runner_group = None
            self.local_runner = SingleAgentEnvRunner(
                cfg.env, cfg.num_envs_per_env_runner, self.module_spec,
                cfg.seed + 1, cfg.env_config)

        self.learner_group = self._make_learner_group()
        self._iteration = 0

    def _make_learner_group(self):
        raise NotImplementedError

    def _transform_module_spec(self, spec_dict):
        """Hook: algorithms with custom rollout modules (e.g. SAC's
        squashed-gaussian actor) rewrite the probed spec here."""
        return spec_dict

    # -- sampling ---------------------------------------------------------

    def _sample(self, num_steps: int) -> List[Dict[str, np.ndarray]]:
        if self.env_runner_group is None:
            return [self.local_runner.sample(num_steps)]
        out = self.env_runner_group.foreach_actor("sample", num_steps)
        self.env_runner_group.probe_and_restore()
        return [b for _, b in out]

    def _sync_runner_weights(self):
        weights = self.learner_group.get_weights()
        if self.env_runner_group is None:
            self.local_runner.set_weights(weights)
        else:
            self.env_runner_group.foreach_actor("set_weights", weights)

    def _runner_metrics(self) -> Dict[str, Any]:
        if self.env_runner_group is None:
            return self.local_runner.get_metrics()
        ms = [m for _, m in self.env_runner_group.foreach_actor("get_metrics")]
        if not ms:
            return {}
        out: Dict[str, Any] = {}
        for k in ms[0]:
            vals = [m[k] for m in ms]
            out[k] = (float(np.mean(vals)) if isinstance(vals[0], float)
                      else int(np.sum(vals)))
        return out

    # -- Trainable interface ---------------------------------------------

    def training_step(self) -> Dict[str, Any]:
        raise NotImplementedError

    def step(self) -> Dict[str, Any]:
        result = self.training_step()
        result.update(self._runner_metrics())
        return result

    def train(self) -> Dict[str, Any]:
        return self.train_step()   # Trainable.train_step adds bookkeeping

    def save_checkpoint(self, checkpoint_dir: str) -> Dict[str, Any]:
        return {"learner_state": self.learner_group.get_state(),
                "iteration": self._iteration}

    def load_checkpoint(self, data, checkpoint_dir: str) -> None:
        if data:
            self.learner_group.set_state(data["learner_state"])
            self._iteration = data.get("iteration", 0)
            self._sync_runner_weights()

    def cleanup(self) -> None:
        if self.env_runner_group is not None:
            for a in self.env_runner_group.actors():
                try:
                    import ray_tpu

                    ray_tpu.kill(a)
                except Exception:
                    pass
        elif self.local_runner is not None:
            self.local_runner.stop()
