"""CQL: conservative Q-learning for offline continuous control.

Reference: ``rllib/algorithms/cql/cql.py`` (config:
bc_iters/temperature/num_actions/min_q_weight) and
``cql_torch_policy.py:83`` (loss). CQL is SAC plus a conservative
penalty on both critics that pushes Q down on out-of-distribution
actions and up on dataset actions:

    penalty_i = w * t * mean(logsumexp(cat_q_i / t)) - w * mean(q_i_data)

where ``cat_q_i`` stacks, per state, Q on uniform-random actions
(importance-corrected by the uniform density), on fresh policy actions
at s, and on fresh policy actions at s' (each corrected by its detached
log-prob) — the "entropy version" the reference calls best. The first
``bc_iters`` updates use a behavior-cloning actor loss
(``alpha * logp_pi - logp(data actions)``), after which the standard
SAC actor objective takes over. The Bellman target follows the
reference in OMITTING the entropy bonus (plain ``r + gamma * min_tq``).

TPU-native: everything (critic + penalty + actor + alpha + polyak) is
one jitted update; the bc_iters switch rides in as a traced step count
through ``lax.cond`` so no recompilation happens at the handoff.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rllib.sac import SACLearner


def _tanh_gaussian_logp(mean, log_std, actions):
    """log-prob of ALREADY-SQUASHED actions under the tanh-gaussian
    (inverse of SACModule.sample_action's change of variables)."""
    import jax
    import jax.numpy as jnp

    a = jnp.clip(actions, -1.0 + 1e-6, 1.0 - 1e-6)
    pre = jnp.arctanh(a)
    std = jnp.exp(log_std)
    logp = (-0.5 * (((pre - mean) / std) ** 2 + 2 * log_std
                    + np.log(2 * np.pi))).sum(-1)
    logp -= (2 * (np.log(2.0) - pre - jax.nn.softplus(-2 * pre))).sum(-1)
    return logp


class CQLLearner(SACLearner):
    """SAC learner + conservative-Q penalty + BC actor warmup.

    Extra config keys over SAC: ``min_q_weight`` (5.0), ``temperature``
    (1.0), ``num_actions`` (4 sampled actions per source), ``bc_iters``
    (0). ``update()`` counts its own iterations for the bc_iters switch.
    """

    def __init__(self, module_spec_dict: Dict[str, Any],
                 config: Dict[str, Any] = None, seed: int = 0):
        super().__init__(module_spec_dict, config, seed)
        self._iter = 0

    def _sample_n(self, params, obs, rng, n):
        """n tanh-gaussian actions per state: obs [B, D] -> ([B*n, A],
        [B*n] detachable logp), with obs tiled to match."""
        import jax.numpy as jnp

        b = obs.shape[0]
        obs_rep = jnp.repeat(obs, n, axis=0)
        act, logp = self.module.sample_action(params, obs_rep, rng)
        return obs_rep, act, logp

    def _update_step(self, params, target_params, log_alpha, opt_state,
                     alpha_state, batch, rng, it=0):
        import jax
        import jax.numpy as jnp
        import optax

        cfg = self.config
        gamma = cfg.get("gamma", 0.99)
        tau = cfg.get("tau", 0.005)
        w = cfg.get("min_q_weight", 5.0)
        temp = cfg.get("temperature", 1.0)
        n_act = int(cfg.get("num_actions", 4))
        bc_iters = int(cfg.get("bc_iters", 0))
        target_entropy = cfg.get("target_entropy",
                                 -float(self.spec.action_dim))
        alpha = jnp.exp(log_alpha)
        a_dim = self.spec.action_dim
        k1, k2, k3, k4, k5 = jax.random.split(rng, 5)

        # -- Bellman target (reference cql_torch_policy.py:185 — NO
        # entropy bonus in the target, unlike SAC) --
        next_act, _ = self.module.sample_action(params, batch["next_obs"], k1)
        tq1, tq2 = self.module.q_values(target_params, batch["next_obs"],
                                        next_act)
        target_q = batch["rewards"] + gamma * (1.0 - batch["dones"]) * (
            jnp.minimum(tq1, tq2))
        target_q = jax.lax.stop_gradient(target_q)

        def loss_fn(p):
            q1, q2 = self.module.q_values(p, batch["obs"], batch["actions"])
            critic = ((q1 - target_q) ** 2 + (q2 - target_q) ** 2).mean()

            # -- conservative penalty --
            b = batch["obs"].shape[0]
            rand = jax.random.uniform(k3, (b * n_act, a_dim),
                                      minval=-1.0, maxval=1.0)
            obs_rep, curr_a, curr_lp = self._sample_n(p, batch["obs"], k4,
                                                      n_act)
            _, next_a, next_lp = self._sample_n(p, batch["next_obs"], k5,
                                                n_act)
            q1_rand, q2_rand = self.module.q_values(p, obs_rep, rand)
            q1_curr, q2_curr = self.module.q_values(p, obs_rep, curr_a)
            # reference evaluates next-state actions at the CURRENT obs
            q1_next, q2_next = self.module.q_values(p, obs_rep, next_a)
            rd = float(np.log(0.5 ** a_dim))  # uniform(-1,1) log-density
            curr_lp = jax.lax.stop_gradient(curr_lp)
            next_lp = jax.lax.stop_gradient(next_lp)

            def cat_q(q_rand, q_curr, q_next):
                # [B, 3*n_act] per-state candidate set
                return jnp.concatenate([
                    (q_rand - rd).reshape(b, n_act),
                    (q_next - next_lp).reshape(b, n_act),
                    (q_curr - curr_lp).reshape(b, n_act),
                ], axis=1)

            lse1 = jax.scipy.special.logsumexp(
                cat_q(q1_rand, q1_curr, q1_next) / temp, axis=1)
            lse2 = jax.scipy.special.logsumexp(
                cat_q(q2_rand, q2_curr, q2_next) / temp, axis=1)
            pen1 = w * temp * lse1.mean() - w * q1.mean()
            pen2 = w * temp * lse2.mean() - w * q2.mean()

            # -- actor: BC warmup for the first bc_iters, then SAC --
            act, logp = self.module.sample_action(p, batch["obs"], k2)
            aq1, aq2 = self.module.q_values(jax.lax.stop_gradient(p),
                                            batch["obs"], act)
            sac_actor = (alpha * logp - jnp.minimum(aq1, aq2)).mean()
            mean, log_std = self.module.actor(p, batch["obs"])
            bc_logp = _tanh_gaussian_logp(mean, log_std, batch["actions"])
            bc_actor = (alpha * logp - bc_logp).mean()
            actor = jax.lax.cond(it < bc_iters, lambda: bc_actor,
                                 lambda: sac_actor)

            total = critic + pen1 + pen2 + actor
            # the observable conservatism: how far OOD Q sits BELOW data Q
            gap = (q1_rand.reshape(b, n_act).mean() - q1.mean())
            return total, (critic, pen1 + pen2, actor, logp, gap)

        (loss, (c_loss, cql_pen, a_loss, logp, gap)), grads = (
            jax.value_and_grad(loss_fn, has_aux=True)(params))
        updates, opt_state = self.optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)

        def alpha_loss_fn(la):
            return -(jnp.exp(la) * jax.lax.stop_gradient(
                logp + target_entropy)).mean()

        a_grad = jax.grad(alpha_loss_fn)(log_alpha)
        a_updates, alpha_state = self.alpha_opt.update(a_grad, alpha_state)
        log_alpha = optax.apply_updates(log_alpha, a_updates)

        target_params = jax.tree.map(
            lambda t, o: (1 - tau) * t + tau * o, target_params, params)
        metrics = {"critic_loss": c_loss, "cql_penalty": cql_pen,
                   "actor_loss": a_loss, "alpha": jnp.exp(log_alpha),
                   "cql_gap": gap, "entropy": -logp.mean()}
        return (params, target_params, log_alpha, opt_state, alpha_state,
                metrics)

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        import jax
        import jax.numpy as jnp

        self._rng, key = jax.random.split(self._rng)
        (self.params, self.target_params, self.log_alpha, self.opt_state,
         self.alpha_state, metrics) = self._update_fn(
            self.params, self.target_params, self.log_alpha,
            self.opt_state, self.alpha_state, batch, key,
            jnp.int32(self._iter))
        self._iter += 1
        return {k: float(jax.device_get(v)) for k, v in metrics.items()}


def train_cql(dataset_path: str, module_spec: Dict[str, Any],
              *, num_iters: int = 200, batch_size: int = 256,
              config: Dict[str, Any] = None, seed: int = 0) -> CQLLearner:
    """Offline CQL training loop over recorded shards (obs, actions,
    rewards, next_obs, dones — :func:`record_episodes` writes them all)."""
    from ray_tpu.rllib.offline import OfflineReader

    reader = OfflineReader(dataset_path)
    data = reader.read_all()
    for key in ("next_obs", "dones"):
        if key not in data:
            raise ValueError(
                f"dataset at {dataset_path!r} has no {key!r} column; "
                "re-record with record_episodes (>= round 5)")
    learner = CQLLearner(module_spec, config, seed=seed)
    rng = np.random.default_rng(seed)
    n = len(data["obs"])
    # Bootstrap mask: TERMINATEDS only — a time-limit truncation is an
    # ordinary state whose successor still has value (reference masks the
    # Bellman target on terminateds, not truncations). Older datasets
    # without the column fall back to the combined dones.
    term = data.get("terminateds", data["dones"]).astype(np.float32)
    for _ in range(num_iters):
        rows = rng.integers(0, n, size=min(batch_size, n))
        learner.update({
            "obs": data["obs"][rows].astype(np.float32),
            "actions": data["actions"][rows].astype(np.float32),
            "rewards": data["rewards"][rows].astype(np.float32),
            "next_obs": data["next_obs"][rows].astype(np.float32),
            "dones": term[rows],
        })
    return learner
