"""Model catalog: encoder/head selection from gym spaces.

Role analog: ``rllib/core/models/catalog.py`` (the reference's Catalog
builds encoder + pi/vf head configs per framework from observation and
action spaces). Here the catalog is TPU-native: every component is a pure
``(init, apply)`` function pair over a param pytree, so modules jit,
shard, and donate like any other JAX state — no framework classes.

Encoders:
  - ``MLPEncoderConfig``  — vector observations.
  - ``CNNEncoderConfig``  — image observations (NHWC, lowered to
    ``lax.conv_general_dilated`` so XLA tiles it onto the MXU; bf16-safe).
  - ``LSTMEncoderConfig`` — recurrent trunk over a ``lax.scan`` (static
    shapes, compiler-friendly; reference uses framework RNN modules).

The catalog's space→config logic mirrors the reference defaults: 3D
uint8/float boxes get the Atari conv stack, flat boxes get an MLP;
Discrete action spaces get a categorical head, Box actions a
diag-Gaussian head.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.rl_module import _act

# (out_channels, kernel, stride) — the classic Atari stack, same defaults
# the reference catalog applies to 64x64..96x96 images.
ATARI_FILTERS: Tuple[Tuple[int, int, int], ...] = (
    (16, 8, 4), (32, 4, 2), (64, 3, 1))


def _dense_init(key, fan_in: int, fan_out: int) -> Dict[str, Any]:
    w = jax.random.normal(key, (fan_in, fan_out), jnp.float32)
    return {"w": w * np.sqrt(2.0 / fan_in),
            "b": jnp.zeros((fan_out,), jnp.float32)}


# ---------------------------------------------------------------------------
# Encoder configs
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class MLPEncoderConfig:
    input_dim: int
    hidden: Tuple[int, ...] = (64, 64)
    activation: str = "tanh"

    @property
    def output_dim(self) -> int:
        return self.hidden[-1] if self.hidden else self.input_dim

    def init(self, key) -> Dict[str, Any]:
        sizes = (self.input_dim, *self.hidden)
        keys = jax.random.split(key, max(1, len(sizes) - 1))
        return {"layers": [
            _dense_init(k, i, o)
            for k, i, o in zip(keys, sizes[:-1], sizes[1:])]}

    def apply(self, params, x):
        act = _act(self.activation)
        x = x.reshape(x.shape[0], -1)
        for lyr in params["layers"]:
            x = act(x @ lyr["w"] + lyr["b"])
        return x


@dataclass(frozen=True)
class CNNEncoderConfig:
    """NHWC conv trunk + flatten + one dense projection."""

    obs_shape: Tuple[int, int, int]  # (H, W, C)
    filters: Tuple[Tuple[int, int, int], ...] = ATARI_FILTERS
    activation: str = "relu"
    dense: int = 256

    @property
    def output_dim(self) -> int:
        return self.dense

    def _conv_shapes(self):
        h, w, c = self.obs_shape
        shapes = []
        for (out_c, k, s) in self.filters:
            shapes.append((k, k, c, out_c))
            h = -(-h // s)  # SAME padding: ceil
            w = -(-w // s)
            c = out_c
        return shapes, h * w * c

    def init(self, key) -> Dict[str, Any]:
        shapes, flat = self._conv_shapes()
        keys = jax.random.split(key, len(shapes) + 1)
        convs = []
        for k, shp in zip(keys[:-1], shapes):
            fan_in = shp[0] * shp[1] * shp[2]
            w = jax.random.normal(k, shp, jnp.float32) * np.sqrt(2.0 / fan_in)
            convs.append({"w": w, "b": jnp.zeros((shp[-1],), jnp.float32)})
        return {"convs": convs, "proj": _dense_init(keys[-1], flat, self.dense)}

    def apply(self, params, x):
        act = _act(self.activation)
        # runners ship flat float obs; restore NHWC (batch, H, W, C)
        x = x.reshape(x.shape[0], *self.obs_shape)
        for (out_c, k, s), lyr in zip(self.filters, params["convs"]):
            x = jax.lax.conv_general_dilated(
                x, lyr["w"], window_strides=(s, s), padding="SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
            x = act(x + lyr["b"])
        x = x.reshape(x.shape[0], -1)
        proj = params["proj"]
        return act(x @ proj["w"] + proj["b"])


@dataclass(frozen=True)
class LSTMEncoderConfig:
    """Single-layer LSTM over a ``lax.scan`` (time-major inside the scan).

    ``apply`` takes ``(params, x, carry)`` with x of shape (B, T, D) and
    returns ``(features (B, T, cell), new_carry)``; ``initial_carry``
    builds zeros. Static shapes end to end — XLA unrolls nothing.
    """

    input_dim: int
    cell_size: int = 128

    @property
    def output_dim(self) -> int:
        return self.cell_size

    def init(self, key) -> Dict[str, Any]:
        k1, k2 = jax.random.split(key)
        n = self.cell_size
        return {"wx": _dense_init(k1, self.input_dim, 4 * n),
                "wh": _dense_init(k2, n, 4 * n)}

    def initial_carry(self, batch: int):
        z = jnp.zeros((batch, self.cell_size), jnp.float32)
        return (z, z)

    def apply(self, params, x, carry=None):
        if carry is None:
            carry = self.initial_carry(x.shape[0])
        wx, wh = params["wx"], params["wh"]

        def step(c, xt):
            h, cell = c
            gates = xt @ wx["w"] + wx["b"] + h @ wh["w"] + wh["b"]
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            cell = jax.nn.sigmoid(f + 1.0) * cell + jax.nn.sigmoid(i) * jnp.tanh(g)
            h = jax.nn.sigmoid(o) * jnp.tanh(cell)
            return (h, cell), h

        carry, ys = jax.lax.scan(step, carry, x.swapaxes(0, 1))
        return ys.swapaxes(0, 1), carry


# ---------------------------------------------------------------------------
# Catalog
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class Catalog:
    """Space-driven component factory (reference ``Catalog`` role).

    ``from_spaces`` picks the encoder family from the observation space
    and the head family from the action space; ``to_module_spec`` folds
    the choice into an ``RLModuleSpec`` so it rides the existing
    dict-serialized spec plumbing across actor boundaries.
    """

    encoder: Any
    action_dim: int
    discrete: bool
    head_hidden: Tuple[int, ...] = ()

    @classmethod
    def from_spaces(cls, obs_space, act_space,
                    hidden: Tuple[int, ...] = (64, 64),
                    activation: str = "tanh") -> "Catalog":
        import gymnasium as gym

        shape = tuple(obs_space.shape or ())
        if len(shape) == 3:
            # The CNN encoder assumes NHWC; a channel-first (C,H,W) space
            # (common Atari wrappers) would be convolved with channels as
            # height (reference catalog's dim checks role). Only shapes
            # that are UNAMBIGUOUSLY channel-first are rejected — odd but
            # valid channel counts (frame-stacked RGB (84,84,12), optical
            # flow (84,84,2)) must keep working.
            if shape[0] <= 4 < shape[-1]:
                raise ValueError(
                    f"3-D Box observation {shape} looks channel-first "
                    "(C,H,W); the CNN encoder expects NHWC. Transpose "
                    "observations (e.g. gymnasium.wrappers."
                    "TransformObservation) before handing the space to "
                    "Catalog.from_spaces.")
            enc = CNNEncoderConfig(obs_shape=shape)
        else:
            enc = MLPEncoderConfig(input_dim=int(np.prod(shape) or 1),
                                   hidden=hidden, activation=activation)
        if isinstance(act_space, gym.spaces.Discrete):
            return cls(encoder=enc, action_dim=int(act_space.n), discrete=True)
        return cls(encoder=enc,
                   action_dim=int(np.prod(act_space.shape)), discrete=False)

    # -- component builders (init, apply) --------------------------------

    def build_encoder(self):
        return self.encoder

    def build_pi_head(self, key):
        return _dense_init(key, self.encoder.output_dim, self.action_dim)

    def build_vf_head(self, key):
        return _dense_init(key, self.encoder.output_dim, 1)

    def to_module_spec(self):
        from ray_tpu.rllib.rl_module import RLModuleSpec

        if isinstance(self.encoder, CNNEncoderConfig):
            return RLModuleSpec(
                observation_dim=int(np.prod(self.encoder.obs_shape)),
                action_dim=self.action_dim, discrete=self.discrete,
                conv_filters=self.encoder.filters,
                obs_shape=self.encoder.obs_shape,
                activation=self.encoder.activation)
        return RLModuleSpec(
            observation_dim=self.encoder.input_dim,
            action_dim=self.action_dim, discrete=self.discrete,
            hidden=self.encoder.hidden, activation=self.encoder.activation)
