"""PPO: clipped-surrogate policy optimization with GAE.

Role analog: ``rllib/algorithms/ppo/ppo.py:421`` (new-API-stack
``_training_step_new_api_stack :430``: synchronous sampling → learner
update → weight sync). The loss matches the reference PPO learner: clipped
surrogate + value loss (clipped) + entropy bonus; advantages via GAE
computed on-host (numpy) before the batch ships to the device.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.learner import JaxLearner, LearnerGroup, masked_mean


def compute_gae(rewards, values, dones, truncateds, last_values,
                gamma: float, lam: float):
    """GAE over [T, N] arrays; episode boundaries cut the recursion.

    Truncated (time-limit) ends bootstrap from the value estimate; true
    terminations zero the bootstrap. Under gymnasium 1.x NEXT_STEP
    autoreset (what SingleAgentEnvRunner steps), ``values[t+1]`` at a
    truncated step t is the value of the episode's TRUE final observation
    (the env returns it from step t; the reset happens one step later), so
    the mid-fragment truncation bootstrap is exact. The reset step itself
    is a garbage transition — callers must drop rows where the batch's
    ``valid`` mask is False before building the train batch.
    """
    t_len, n = rewards.shape
    adv = np.zeros((t_len, n), np.float32)
    last_gae = np.zeros((n,), np.float32)
    next_value = last_values
    for t in range(t_len - 1, -1, -1):
        # bootstrap unless a true termination happened at step t
        nonterminal = 1.0 - dones[t].astype(np.float32)
        delta = rewards[t] + gamma * next_value * nonterminal - values[t]
        episode_end = np.logical_or(dones[t], truncateds[t])
        last_gae = delta + gamma * lam * nonterminal * last_gae * (
            1.0 - truncateds[t].astype(np.float32))
        adv[t] = last_gae
        # reset the recursion across episode boundaries
        last_gae = last_gae * (1.0 - episode_end.astype(np.float32))
        next_value = values[t]
    returns = adv + values
    return adv, returns


def ppo_loss(module, cfg: Dict, params, batch):
    """The clipped-surrogate PPO loss for ONE module on its flat batch.

    Factored out of the learner so the multi-agent learner can sum it per
    policy module (reference loss math: ppo_torch_learner
    ``compute_loss_for_module`` role)."""
    import jax.numpy as jnp

    clip = cfg.get("clip_param", 0.2)
    vf_clip = cfg.get("vf_clip_param", 10.0)
    vf_coeff = cfg.get("vf_loss_coeff", 0.5)
    ent_coeff = cfg.get("entropy_coeff", 0.0)

    mask = batch.get("loss_mask")
    out = module.forward_train(params, batch["obs"])
    logp, entropy = module.logp_entropy(out, batch["actions"])
    ratio = jnp.exp(logp - batch["action_logp"])
    adv = batch["advantages"]
    surr = jnp.minimum(
        ratio * adv,
        jnp.clip(ratio, 1 - clip, 1 + clip) * adv)
    policy_loss = -masked_mean(surr, mask)

    vf = out["vf_preds"]
    vf_err = jnp.square(vf - batch["value_targets"])
    vf_clipped = batch["vf_preds"] + jnp.clip(
        vf - batch["vf_preds"], -vf_clip, vf_clip)
    vf_err_clipped = jnp.square(vf_clipped - batch["value_targets"])
    vf_loss = masked_mean(jnp.maximum(vf_err, vf_err_clipped), mask)

    ent = masked_mean(entropy, mask)
    loss = policy_loss + vf_coeff * vf_loss - ent_coeff * ent
    kl = masked_mean(batch["action_logp"] - logp, mask)
    return loss, {
        "policy_loss": policy_loss,
        "vf_loss": vf_loss,
        "entropy": ent,
        "kl": kl,
    }


class PPOLearner(JaxLearner):
    def compute_loss(self, params, batch):
        return ppo_loss(self.module, self.config, params, batch)


class PPOConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or PPO)
        self.lam = 0.95
        self.clip_param = 0.2
        self.vf_clip_param = 10.0
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.0
        self.lr = 5e-5


class PPO(Algorithm):
    config_cls = PPOConfig

    def _make_learner_group(self):
        cfg = self.algo_config
        learner_cfg = {
            "lr": cfg.lr, "grad_clip": cfg.grad_clip,
            "clip_param": getattr(cfg, "clip_param", 0.2),
            "vf_clip_param": getattr(cfg, "vf_clip_param", 10.0),
            "vf_loss_coeff": getattr(cfg, "vf_loss_coeff", 0.5),
            "entropy_coeff": getattr(cfg, "entropy_coeff", 0.0),
        }
        return LearnerGroup(PPOLearner, self.module_spec, learner_cfg,
                            num_learners=cfg.num_learners, seed=cfg.seed)

    def training_step(self) -> Dict[str, Any]:
        import jax

        cfg = self.algo_config
        # 1. synchronous parallel sampling (reference ppo.py:435)
        batches = self._sample(cfg.rollout_fragment_length)
        train_batch = self._postprocess(batches)
        # 2. learner update (reference ppo.py:478)
        metrics = self.learner_group.update(
            train_batch,
            minibatch_size=cfg.minibatch_size,
            num_epochs=cfg.num_epochs,
        )
        # 3. broadcast new weights to env runners (reference ppo.py:501)
        self._sync_runner_weights()
        self._iteration += 1
        metrics["num_env_steps_sampled"] = int(
            len(train_batch["obs"]))
        return metrics

    def _postprocess(self, batches: List[Dict[str, np.ndarray]]
                     ) -> Dict[str, np.ndarray]:
        import jax

        cfg = self.algo_config
        outs = []
        weights = None
        for b in batches:
            # bootstrap value for the last observation of each env
            if weights is None:
                weights = self.learner_group.get_weights()
            module = (self.local_runner.module if self.local_runner
                      else None)
            if module is None:
                from ray_tpu.rllib.rl_module import RLModuleSpec

                module = RLModuleSpec(**self.module_spec).build()
            last_out = module.forward_train(weights, b["next_obs"])
            last_values = np.asarray(last_out["vf_preds"])
            adv, ret = compute_gae(
                b["rewards"], b["vf_preds"], b["terminateds"],
                b["truncateds"], last_values, cfg.gamma,
                getattr(cfg, "lam", 0.95))
            t_len, n = b["rewards"].shape
            # drop autoreset reset-step rows (action ignored by the env,
            # reward 0, obs = previous episode's final obs)
            mask = b.get("valid", np.ones((t_len, n), bool)).reshape(-1)
            flat = {
                "obs": b["obs"].reshape(t_len * n, -1)[mask],
                "actions": b["actions"].reshape(
                    t_len * n, *b["actions"].shape[2:])[mask],
                "action_logp": b["action_logp"].reshape(-1)[mask],
                "vf_preds": b["vf_preds"].reshape(-1)[mask],
                "advantages": adv.reshape(-1)[mask],
                "value_targets": ret.reshape(-1)[mask],
            }
            outs.append(flat)
        merged = {k: np.concatenate([o[k] for o in outs]) for k in outs[0]}
        # advantage normalization (reference PPO default)
        a = merged["advantages"]
        merged["advantages"] = ((a - a.mean()) / max(a.std(), 1e-6)
                                ).astype(np.float32)
        return merged
