"""APPO: asynchronous PPO — IMPALA's pipeline with PPO's clipped loss.

Role analog: ``rllib/algorithms/appo/appo.py`` — the throughput RL family:
async sampling + v-trace off-policy correction (inherited wholesale from
the IMPALA machinery here), but the policy gradient is PPO's clipped
surrogate against the BEHAVIOR policy, optionally with an adaptive KL
penalty (reference ``use_kl_loss`` / ``kl_coeff`` / ``kl_target``).

TPU-native stance: identical to IMPALA's — CPU env-runner actors sample
asynchronously; ONE jitted learner update on the device mesh; v-trace on
the host/aggregators. The adaptive KL coefficient updates on the driver
between steps (a scalar; no recompile — it rides the batch).
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rllib.impala import IMPALA, IMPALAConfig, ImpalaLearner
from ray_tpu.rllib.learner import LearnerGroup, masked_mean


class APPOLearner(ImpalaLearner):
    def compute_loss(self, params, batch):
        import jax.numpy as jnp

        cfg = self.config
        clip = cfg.get("clip_param", 0.2)
        vf_coeff = cfg.get("vf_loss_coeff", 0.5)
        ent_coeff = cfg.get("entropy_coeff", 0.01)
        use_kl = cfg.get("use_kl_loss", False)

        mask = batch.get("loss_mask")
        out = self.module.forward_train(params, batch["obs"])
        logp, entropy = self.module.logp_entropy(out, batch["actions"])
        # clipped surrogate vs the BEHAVIOR policy, advantages already
        # v-trace-corrected (reference appo loss shape)
        ratio = jnp.exp(logp - batch["action_logp"])
        adv = batch["pg_advantages"]
        surr = jnp.minimum(ratio * adv,
                           jnp.clip(ratio, 1 - clip, 1 + clip) * adv)
        pg_loss = -masked_mean(surr, mask)
        vf_loss = masked_mean(jnp.square(out["vf_preds"] - batch["vs"]),
                              mask)
        ent = masked_mean(entropy, mask)
        kl = masked_mean(batch["action_logp"] - logp, mask)
        loss = pg_loss + vf_coeff * vf_loss - ent_coeff * ent
        if use_kl:
            # kl_coeff rides the BATCH, not the jitted constants: the
            # driver's adaptive update must not trigger a recompile
            loss = loss + batch["kl_coeff"][0] * kl
        return loss, {"policy_loss": pg_loss, "vf_loss": vf_loss,
                      "entropy": ent, "kl": kl}


class APPOConfig(IMPALAConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or APPO)
        self.clip_param = 0.2
        self.use_kl_loss = False
        self.kl_coeff = 0.2
        self.kl_target = 0.01
        self.lr = 5e-4
        # unlike IMPALA's single pass, the clipped surrogate tolerates
        # minibatch re-use (reference APPO num_sgd_iter role)
        self.num_epochs = 2
        self.minibatch_size = 128


class APPO(IMPALA):
    config_cls = APPOConfig

    def _setup_algo(self):
        super()._setup_algo()
        self._kl_coeff = float(getattr(self.algo_config, "kl_coeff", 0.2))

    def _make_learner_group(self):
        cfg = self.algo_config
        learner_cfg = {
            "lr": cfg.lr, "grad_clip": cfg.grad_clip,
            "clip_param": cfg.clip_param,
            "vf_loss_coeff": cfg.vf_loss_coeff,
            "entropy_coeff": cfg.entropy_coeff,
            "use_kl_loss": cfg.use_kl_loss,
        }
        return LearnerGroup(APPOLearner, self.module_spec, learner_cfg,
                            num_learners=cfg.num_learners, seed=cfg.seed)

    def _postprocess(self, batches) -> Dict[str, np.ndarray]:
        out = super()._postprocess(batches)
        if getattr(self.algo_config, "use_kl_loss", False):
            n = len(out["obs"])
            out["kl_coeff"] = np.full(n, self._kl_coeff, np.float32)
        return out

    def training_step(self) -> Dict[str, Any]:
        metrics = super().training_step()
        # adaptive KL (reference appo update_kl): double/halve toward the
        # target measured on this step's update
        if getattr(self.algo_config, "use_kl_loss", False) \
                and "kl" in metrics:
            target = float(getattr(self.algo_config, "kl_target", 0.01))
            kl = abs(float(metrics["kl"]))
            if kl > 2.0 * target:
                self._kl_coeff *= 1.5
            elif kl < 0.5 * target:
                self._kl_coeff *= 0.5
            metrics["kl_coeff"] = self._kl_coeff
        return metrics
