"""ray_tpu.rllib — reinforcement learning: env-runner actors + JAX learners.

Role analog: ``rllib/`` new API stack (SURVEY §2.7): AlgorithmConfig →
Algorithm (a Tune Trainable) → EnvRunnerGroup (CPU actors, fault-tolerant
manager) + LearnerGroup (JAX learners; on TPU one learner owns a mesh and
gradient sync is XLA psum, not DDP). PPO (sync, GAE) and IMPALA (async,
V-trace) ship first; replay buffers cover the off-policy family.
"""

from ray_tpu.rllib.actor_manager import FaultTolerantActorManager
from ray_tpu.rllib.catalog import (ATARI_FILTERS, Catalog, CNNEncoderConfig,
                                   LSTMEncoderConfig, MLPEncoderConfig)
from ray_tpu.rllib.anakin import AnakinPPO
from ray_tpu.rllib.appo import APPO, APPOConfig, APPOLearner
from ray_tpu.rllib.dqn import DQN, DQNConfig, DQNLearner
from ray_tpu.rllib.jax_env import CartPoleJax, make_jax_env
from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.env_runner import SingleAgentEnvRunner
from ray_tpu.rllib.impala import IMPALA, IMPALAConfig, ImpalaLearner, \
    compute_vtrace
from ray_tpu.rllib.learner import JaxLearner, LearnerGroup
from ray_tpu.rllib.ppo import PPO, PPOConfig, PPOLearner, compute_gae
from ray_tpu.rllib.replay import PrioritizedReplayBuffer, ReplayBuffer
from ray_tpu.rllib.sac import SAC, SACConfig, SACLearner
from ray_tpu.rllib.connectors import (
    ClipActions,
    ConnectorPipelineV2,
    ConnectorV2,
    FlattenObservations,
    NormalizeObservations,
    ScaleActions,
)
from ray_tpu.rllib.cql import CQLLearner, train_cql
from ray_tpu.rllib.dreamerv3 import (DreamerV3Learner,
                                     train_dreamerv3)
from ray_tpu.rllib.offline import (
    BCLearner,
    MARWILLearner,
    OfflineReader,
    OfflineWriter,
    record_episodes,
    train_bc,
    train_marwil,
)
from ray_tpu.rllib.multi_agent import (
    DebugCooperativeMatch,
    MultiAgentEnv,
    MultiAgentEnvRunner,
    MultiAgentPPO,
    MultiAgentPPOConfig,
    MultiAgentPPOLearner,
    MultiAgentRLModule,
    MultiAgentRLModuleSpec,
)
from ray_tpu.rllib.rl_module import JaxRLModule, RLModuleSpec

__all__ = [
    "Algorithm",
    "AnakinPPO",
    "APPO",
    "APPOConfig",
    "APPOLearner",
    "DQN",
    "DQNConfig",
    "DQNLearner",
    "CartPoleJax",
    "make_jax_env",
    "AlgorithmConfig",
    "SingleAgentEnvRunner",
    "FaultTolerantActorManager",
    "JaxLearner",
    "LearnerGroup",
    "JaxRLModule",
    "Catalog",
    "CNNEncoderConfig",
    "MLPEncoderConfig",
    "LSTMEncoderConfig",
    "ATARI_FILTERS",
    "RLModuleSpec",
    "MultiAgentEnv",
    "MultiAgentEnvRunner",
    "MultiAgentPPO",
    "MultiAgentPPOConfig",
    "MultiAgentPPOLearner",
    "MultiAgentRLModule",
    "MultiAgentRLModuleSpec",
    "DebugCooperativeMatch",
    "PPO",
    "PPOConfig",
    "PPOLearner",
    "compute_gae",
    "IMPALA",
    "IMPALAConfig",
    "ImpalaLearner",
    "compute_vtrace",
    "ReplayBuffer",
    "PrioritizedReplayBuffer",
    "SAC",
    "SACConfig",
    "SACLearner",
    "ConnectorV2",
    "ConnectorPipelineV2",
    "FlattenObservations",
    "NormalizeObservations",
    "ClipActions",
    "ScaleActions",
    "BCLearner",
    "CQLLearner",
    "DreamerV3Learner",
    "train_dreamerv3",
    "MARWILLearner",
    "OfflineReader",
    "OfflineWriter",
    "record_episodes",
    "train_bc",
    "train_cql",
    "train_marwil",
]
