"""EnvRunner: CPU actors stepping vectorized gymnasium envs.

Role analog: ``rllib/env/single_agent_env_runner.py`` over gymnasium vector
envs, managed by ``EnvRunnerGroup`` (``env_runner_group.py:66``) through a
fault-tolerant actor manager. Env runners are CPU-only; the sampled batch
ships to the (TPU) learner as numpy, so the host/device split matches the
reference's sampler/learner split.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np


class SingleAgentEnvRunner:
    """Steps N vectorized env copies with the current module weights."""

    def __init__(self, env_name: str, num_envs: int = 1,
                 module_spec: Optional[Dict[str, Any]] = None,
                 seed: int = 0, env_config: Optional[Dict[str, Any]] = None,
                 env_to_module=None, module_to_env=None):
        import gymnasium as gym

        import jax

        from ray_tpu.rllib.rl_module import RLModuleSpec

        self.env = gym.make_vec(env_name, num_envs=num_envs,
                                vectorization_mode="sync",
                                **(env_config or {}))
        self.num_envs = num_envs
        if module_spec is None:
            from ray_tpu.rllib.rl_module import spec_for_env

            self.spec = spec_for_env(self.env)
            self.module = self.spec.build()
        elif module_spec.get("kind") == "sac":
            from ray_tpu.rllib.sac import SACModuleSpec, SACRolloutModule

            self.spec = SACModuleSpec(
                observation_dim=module_spec["observation_dim"],
                action_dim=module_spec["action_dim"])
            self.module = SACRolloutModule(self.spec)
        else:
            self.spec = RLModuleSpec(**{k: v for k, v in module_spec.items()
                                        if k != "kind"})
            self.module = self.spec.build()
        self.params = self.module.init(jax.random.PRNGKey(seed))
        self._rng = jax.random.PRNGKey(seed + 1)
        from ray_tpu.util.device_plane import registered_jit

        self._explore_fn = registered_jit(self.module.forward_exploration,
                                          name="rllib::forward_exploration",
                                          component="rllib")
        self._obs, _ = self.env.reset(seed=seed)
        self._episode_returns = np.zeros(num_envs)
        self._episode_lens = np.zeros(num_envs, dtype=np.int64)
        self._completed_returns: List[float] = []
        self._completed_lens: List[int] = []
        # gymnasium 1.x NEXT_STEP autoreset: the step after term|trunc is a
        # reset step — the env ignores the action and returns the new
        # episode's first obs with reward 0. Those transitions are not valid
        # training samples; track episode ends across fragment boundaries so
        # the first step of the next sample() call is masked too.
        self._prev_finished = np.zeros(num_envs, dtype=bool)
        # ConnectorV2 pipelines (reference rllib/connectors/connector_v2.py):
        # env->module transforms observations BEFORE the forward pass (the
        # batch stores transformed obs so training sees what the module
        # saw); module->env transforms actions before env.step.
        self._env_to_module = env_to_module
        self._module_to_env = module_to_env
        if module_to_env is None and getattr(self.module, "squashed", False):
            # tanh policies emit [-1, 1]; map to the env's true bounds
            # (reference unsquash_action) or envs like Pendulum ([-2, 2])
            # would only ever see half their action range
            space = self.env.single_action_space
            low = np.asarray(getattr(space, "low", -1.0), np.float32)
            high = np.asarray(getattr(space, "high", 1.0), np.float32)
            if np.all(np.isfinite(low)) and np.all(np.isfinite(high)):
                from ray_tpu.rllib.connectors import ScaleActions

                self._module_to_env = ScaleActions(low, high)

    def set_weights(self, params) -> None:
        self.params = params

    def get_spec(self) -> Dict[str, Any]:
        from dataclasses import asdict

        return asdict(self.spec)

    def sample(self, num_steps: int = 200) -> Dict[str, np.ndarray]:
        """Collect a rollout of ``num_steps`` vector steps.

        Returns a flat batch dict with [T*N, ...] arrays plus episode
        metrics; bootstrap values handled learner-side via ``next_obs``.
        """
        import jax

        obs_buf, act_buf, logp_buf, vf_buf = [], [], [], []
        rew_buf, done_buf, trunc_buf, valid_buf = [], [], [], []
        obs = self._obs
        for _ in range(num_steps):
            self._rng, key = jax.random.split(self._rng)
            mod_obs = obs.astype(np.float32).reshape(self.num_envs, -1)
            if self._env_to_module is not None:
                mod_obs = np.asarray(self._env_to_module(mod_obs),
                                     np.float32)
            out = self._explore_fn(self.params, mod_obs, key)
            action = np.asarray(out["actions"])
            env_action = action if self.spec.discrete else action.reshape(
                self.env.action_space.shape)
            if self._module_to_env is not None:
                env_action = self._module_to_env(env_action)
            next_obs, reward, term, trunc, _ = self.env.step(env_action)
            obs_buf.append(mod_obs)
            act_buf.append(action)
            logp_buf.append(np.asarray(out["action_logp"]))
            vf_buf.append(np.asarray(out["vf_preds"]))
            rew_buf.append(reward)
            done_buf.append(term)
            trunc_buf.append(trunc)
            valid = ~self._prev_finished
            valid_buf.append(valid)
            self._episode_returns += reward * valid
            self._episode_lens += valid
            finished = np.logical_or(term, trunc)
            self._prev_finished = finished
            for i in np.flatnonzero(finished):
                self._completed_returns.append(float(self._episode_returns[i]))
                self._completed_lens.append(int(self._episode_lens[i]))
                self._episode_returns[i] = 0.0
                self._episode_lens[i] = 0
            obs = next_obs
        self._obs = obs
        batch = {
            "obs": np.stack(obs_buf).astype(np.float32),          # [T, N, D]
            "actions": np.stack(act_buf),
            "action_logp": np.stack(logp_buf).astype(np.float32),
            "vf_preds": np.stack(vf_buf).astype(np.float32),
            "rewards": np.stack(rew_buf).astype(np.float32),
            "terminateds": np.stack(done_buf),
            "truncateds": np.stack(trunc_buf),
            "valid": np.stack(valid_buf),                          # [T, N]
            "next_obs": self._final_obs(obs),
        }
        return batch

    def _final_obs(self, obs) -> np.ndarray:
        out = obs.reshape(self.num_envs, -1).astype(np.float32)
        if self._env_to_module is not None:
            # apply WITHOUT updating stateful connectors: the next
            # fragment re-feeds these rows as its first obs, and counting
            # them twice would skew running statistics
            try:
                out = np.asarray(self._env_to_module(out, update=False),
                                 np.float32)
            except TypeError:
                out = np.asarray(self._env_to_module(out), np.float32)
        return out

    def get_metrics(self) -> Dict[str, Any]:
        m = {
            "episode_return_mean": (float(np.mean(self._completed_returns[-100:]))
                                    if self._completed_returns else 0.0),
            "episode_len_mean": (float(np.mean(self._completed_lens[-100:]))
                                 if self._completed_lens else 0.0),
            "num_episodes": len(self._completed_returns),
        }
        return m

    def ping(self) -> bool:
        return True

    def stop(self) -> None:
        self.env.close()
