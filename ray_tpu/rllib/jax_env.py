"""Pure-JAX vectorized environments (the Anakin/podracer substrate).

Green-field relative to the reference (gym envs are host-side there). For
TPU-native RL the env itself is a jitted pure function, so rollout +
learning fuse into ONE XLA program with no host round-trips (Podracer
"Anakin" architecture, Hessel et al. 2021 — listed in PAPERS.md; pattern
only, reimplemented from the public equations of CartPole dynamics).
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp


class EnvState(NamedTuple):
    obs: jax.Array        # [D] physical state
    t: jax.Array          # step counter
    key: jax.Array


class StepOut(NamedTuple):
    state: EnvState
    obs: jax.Array
    reward: jax.Array
    done: jax.Array


class CartPoleJax:
    """CartPole-v1 dynamics as pure functions (standard published physics:
    gravity 9.8, masscart 1.0, masspole 0.1, pole half-length 0.5,
    force 10, dt 0.02, termination at |x|>2.4, |theta|>12deg, 500 steps)."""

    observation_dim = 4
    action_dim = 2
    discrete = True
    max_steps = 500

    def reset(self, key: jax.Array) -> EnvState:
        key, sub = jax.random.split(key)
        obs = jax.random.uniform(sub, (4,), minval=-0.05, maxval=0.05)
        return EnvState(obs=obs, t=jnp.zeros((), jnp.int32), key=key)

    def step(self, state: EnvState, action: jax.Array) -> StepOut:
        x, x_dot, theta, theta_dot = state.obs
        force = jnp.where(action == 1, 10.0, -10.0)
        costh = jnp.cos(theta)
        sinth = jnp.sin(theta)
        total_mass = 1.1
        polemass_length = 0.05
        temp = (force + polemass_length * theta_dot ** 2 * sinth) / total_mass
        theta_acc = (9.8 * sinth - costh * temp) / (
            0.5 * (4.0 / 3.0 - 0.1 * costh ** 2 / total_mass))
        x_acc = temp - polemass_length * theta_acc * costh / total_mass
        dt = 0.02
        obs = jnp.stack([
            x + dt * x_dot,
            x_dot + dt * x_acc,
            theta + dt * theta_dot,
            theta_dot + dt * theta_acc,
        ])
        t = state.t + 1
        done = (jnp.abs(obs[0]) > 2.4) | (jnp.abs(obs[2]) > 0.2095) | \
            (t >= self.max_steps)
        # auto-reset on done (standard vectorized-env semantics)
        key, sub = jax.random.split(state.key)
        reset_obs = jax.random.uniform(sub, (4,), minval=-0.05, maxval=0.05)
        next_obs = jnp.where(done, reset_obs, obs)
        next_t = jnp.where(done, 0, t)
        new_state = EnvState(obs=next_obs, t=next_t, key=key)
        return StepOut(state=new_state, obs=next_obs,
                       reward=jnp.ones(()), done=done)


REGISTRY = {"CartPole-v1": CartPoleJax}


def make_jax_env(name: str):
    try:
        return REGISTRY[name]()
    except KeyError:
        raise ValueError(
            f"no pure-JAX env {name!r}; have {sorted(REGISTRY)}")
