"""FaultTolerantActorManager: health-checked fan-out over actor pools.

Role analog: ``rllib/utils/actor_manager.py:196`` — EnvRunnerGroup's
resilience layer: issue calls to many actors, harvest what succeeds, mark
and restart the dead.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import ray_tpu


class FaultTolerantActorManager:
    def __init__(self, make_actor: Callable[[int], Any], num_actors: int):
        self._make_actor = make_actor
        self._actors: Dict[int, Any] = {
            i: make_actor(i) for i in range(num_actors)}
        self._healthy: Dict[int, bool] = {i: True for i in self._actors}
        self.num_restarts = 0

    def __len__(self):
        return len(self._actors)

    def healthy_ids(self) -> List[int]:
        return [i for i, h in self._healthy.items() if h]

    def foreach_actor(self, fn_name: str, *args,
                      timeout: Optional[float] = None,
                      **kwargs) -> List[Tuple[int, Any]]:
        """Call ``fn_name`` on every healthy actor; returns (id, result)
        for the ones that succeeded, marking failures unhealthy."""
        refs = {}
        for i in self.healthy_ids():
            method = getattr(self._actors[i], fn_name)
            refs[i] = method.remote(*args, **kwargs)
        out: List[Tuple[int, Any]] = []
        for i, ref in refs.items():
            try:
                out.append((i, ray_tpu.get(ref, timeout=timeout)))
            except Exception:
                self._healthy[i] = False
        return out

    def probe_and_restore(self) -> int:
        """Health-check unhealthy actors; recreate the dead ones."""
        restored = 0
        for i, healthy in list(self._healthy.items()):
            if healthy:
                continue
            try:
                ray_tpu.get(self._actors[i].ping.remote(), timeout=5)
                self._healthy[i] = True
            except Exception:
                try:
                    ray_tpu.kill(self._actors[i])
                except Exception:
                    pass
                self._actors[i] = self._make_actor(i)
                self._healthy[i] = True
                self.num_restarts += 1
                restored += 1
        return restored

    def actors(self) -> List[Any]:
        return [self._actors[i] for i in self.healthy_ids()]
