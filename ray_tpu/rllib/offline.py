"""Offline RL: experience IO + behavior cloning.

Role analog: ``rllib/offline/`` (readers/writers, BC in
``rllib/algorithms/bc/``). Experiences persist as npz shards readable into
:mod:`ray_tpu.data` datasets, so offline training rides the same streaming
data plane as everything else.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Iterator, List, Optional

import numpy as np

from ray_tpu.rllib.learner import JaxLearner


class OfflineWriter:
    """Append sample batches as npz shards (reference JsonWriter role —
    npz keeps tensors binary and mmap-friendly)."""

    def __init__(self, path: str, max_rows_per_shard: int = 50_000):
        self.path = path
        os.makedirs(path, exist_ok=True)
        self.max_rows = max_rows_per_shard
        self._pending: List[Dict[str, np.ndarray]] = []
        self._rows = 0
        self._shard = 0

    def write(self, batch: Dict[str, np.ndarray]) -> None:
        batch = {k: np.asarray(v) for k, v in batch.items()}
        self._pending.append(batch)
        self._rows += len(next(iter(batch.values())))
        if self._rows >= self.max_rows:
            self.flush()

    def flush(self) -> None:
        if not self._pending:
            return
        merged = {k: np.concatenate([b[k] for b in self._pending])
                  for k in self._pending[0]}
        out = os.path.join(self.path, f"shard-{self._shard:05d}.npz")
        # write through an open handle with a non-.npz temp name: a
        # crashed/concurrent flush must never leave a file the reader's
        # .npz glob can pick up
        tmp = out + f".tmp-{os.getpid()}"
        with open(tmp, "wb") as f:
            np.savez_compressed(f, **merged)
        os.rename(tmp, out)
        self._shard += 1
        self._pending = []
        self._rows = 0


class OfflineReader:
    """Iterate shards written by :class:`OfflineWriter`."""

    def __init__(self, path: str):
        self.path = path
        self.shards = sorted(
            os.path.join(path, f) for f in os.listdir(path)
            if f.endswith(".npz"))
        if not self.shards:
            raise FileNotFoundError(f"no npz shards under {path!r}")

    def read_all(self) -> Dict[str, np.ndarray]:
        parts = [dict(np.load(s)) for s in self.shards]
        return {k: np.concatenate([p[k] for p in parts]) for k in parts[0]}

    def iter_batches(self, batch_size: int, *, shuffle: bool = True,
                     seed: int = 0) -> Iterator[Dict[str, np.ndarray]]:
        data = self.read_all()
        n = len(next(iter(data.values())))
        idx = np.arange(n)
        if shuffle:
            np.random.default_rng(seed).shuffle(idx)
        for start in range(0, n - batch_size + 1, batch_size):
            rows = idx[start:start + batch_size]
            yield {k: v[rows] for k, v in data.items()}

    def _refresh_shards(self) -> None:
        """Re-list the directory: a writer appending shards between epochs
        (online data collection interleaved with training) must become
        visible to the next read."""
        shards = sorted(
            os.path.join(self.path, f) for f in os.listdir(self.path)
            if f.endswith(".npz"))
        if shards:
            self.shards = shards

    def _sequence_windows(self, seq_len: int) -> list:
        """Build (and cache) the [T, ...] sequence windows for
        :meth:`iter_sequences` — the expensive part, independent of the
        shuffle seed, so repeated epochs don't re-read the shards.

        The cache is keyed on (seq_len, shard list): shards appended after
        the first epoch invalidate it instead of being silently ignored
        (ADVICE r5 — the old key was seq_len alone)."""
        self._refresh_shards()
        fingerprint = (seq_len, tuple(self.shards))
        cache = getattr(self, "_window_cache", None)
        if cache is not None and cache[0] == fingerprint:
            return cache[1]
        data = self.read_all()
        dones = data["dones"].astype(bool)
        terminateds = data.get("terminateds", data["dones"]).astype(bool)
        obs = data["obs"].astype(np.float32)
        next_obs = data["next_obs"].astype(np.float32)
        actions = data["actions"]
        rewards = data["rewards"].astype(np.float32)

        windows = []
        ep_start = 0
        bounds = list(np.flatnonzero(dones))
        if not bounds or bounds[-1] != len(dones) - 1:
            bounds.append(len(dones) - 1)
        for end in bounds:
            a, b = ep_start, end
            ep_start = end + 1
            # Per-episode arrays in the Dreamer replay convention —
            # index i describes ARRIVING at eobs[i]:
            #   eobs  = [obs_a .. obs_b, successor of obs_b]
            #   erew[i] = reward of the transition INTO eobs[i] (0 for
            #             the episode's true first state)
            #   econt[i] = that transition was non-TERMINAL (truncation
            #             bootstraps, so only terminateds gate it)
            # Including the successor obs is what puts the terminal
            # state (continue=0) and the final reward into the stream —
            # without it the continue head only ever sees 1.
            eobs = np.concatenate([obs[a:b + 1], next_obs[b:b + 1]])
            eact = np.concatenate([actions[a:b + 1],
                                   np.zeros_like(actions[b:b + 1])])
            erew = np.concatenate([[0.0], rewards[a:b + 1]])
            econt = np.concatenate(
                [np.ones(b + 1 - a, np.float32),
                 1.0 - terminateds[b:b + 1].astype(np.float32)])
            L = len(eobs)
            for w0 in range(0, L - seq_len + 1, seq_len):
                s = slice(w0, w0 + seq_len)
                windows.append({
                    "obs": eobs[s], "actions": eact[s],
                    "rewards": erew[s].astype(np.float32),
                    "continues": econt[s].astype(np.float32)})
        if not windows:
            raise ValueError(
                f"no episode yields a full {seq_len}-step window")
        self._window_cache = (fingerprint, windows)
        return windows

    def iter_sequences(self, seq_len: int, batch_size: int, *,
                       shuffle: bool = True, seed: int = 0
                       ) -> Iterator[Dict[str, np.ndarray]]:
        """[B, T] sequence windows for model-based learners (DreamerV3).

        Episodes are recovered by splitting the flat stream at ``dones``
        — valid only for recordings whose rows are episode-contiguous
        (``record_episodes(..., num_envs=1)``; multi-env recordings
        interleave envs time-major and cannot be re-segmented). Each
        episode is extended with its terminal successor observation
        (continue=0 there unless truncated), windows are non-overlapping
        within an episode, and tails shorter than ``seq_len`` are
        dropped. Raises when the dataset yields fewer than
        ``batch_size`` windows (a silent empty iterator would hang
        epoch loops).
        """
        windows = self._sequence_windows(seq_len)
        if len(windows) < batch_size:
            raise ValueError(
                f"dataset yields {len(windows)} windows of len "
                f"{seq_len} < batch_size {batch_size}")
        idx = np.arange(len(windows))
        if shuffle:
            np.random.default_rng(seed).shuffle(idx)
        for start in range(0, len(idx) - batch_size + 1, batch_size):
            rows = idx[start:start + batch_size]
            yield {k: np.stack([windows[i][k] for i in rows])
                   for k in windows[0]}

    def as_dataset(self, parallelism: int = 8):
        """The shards as a ray_tpu.data Dataset of row blocks."""
        import ray_tpu
        from ray_tpu.data.dataset import Dataset

        whole = self.read_all()
        n = len(next(iter(whole.values())))
        size = max(1, (n + parallelism - 1) // parallelism)
        blocks = [{k: v[i:i + size] for k, v in whole.items()}
                  for i in range(0, n, size)]
        return Dataset([ray_tpu.put(b) for b in (blocks or [{}])])


def reward_to_go(rewards: np.ndarray, dones: np.ndarray,
                 gamma: float) -> np.ndarray:
    """Discounted reward-to-go over [T, N] env columns, reset at dones.
    Episodes cut off by the end of recording keep the observed suffix sum
    (standard for offline data)."""
    returns = np.zeros_like(rewards, dtype=np.float32)
    acc = np.zeros(rewards.shape[1], np.float32)
    for t in range(rewards.shape[0] - 1, -1, -1):
        acc = rewards[t] + gamma * acc * (1.0 - dones[t])
        returns[t] = acc
    return returns


def record_episodes(env_name: str, path: str, num_steps: int = 1000,
                    policy=None, seed: int = 0,
                    num_envs: int = 4, gamma: float = 0.99) -> OfflineWriter:
    """Roll out a policy (default: current random-init module) and persist
    the experience — the 'generate offline data' workflow.

    Shards carry everything the offline algorithms need: BC uses
    (obs, actions); MARWIL adds ``returns`` (discounted reward-to-go,
    computed over full recorded episodes BEFORE env columns are
    flattened, since flattening interleaves envs); CQL adds
    (next_obs, dones). Chunks are accumulated before the return pass so
    episodes spanning chunk boundaries get exact reward-to-go."""
    from ray_tpu.rllib.env_runner import SingleAgentEnvRunner

    runner = SingleAgentEnvRunner(env_name, num_envs=num_envs, seed=seed)
    if policy is not None:
        runner.set_weights(policy)
    writer = OfflineWriter(path)
    chunks = []
    steps = 0
    while steps < num_steps:
        b = runner.sample(num_steps=min(200, num_steps - steps))
        chunks.append(b)
        steps += b["rewards"].shape[0]
    cat = {k: np.concatenate([c[k] for c in chunks], axis=0)
           for k in ("obs", "actions", "rewards", "terminateds",
                     "truncateds", "valid")}
    t_len, n = cat["rewards"].shape
    dones = np.logical_or(cat["terminateds"],
                          cat["truncateds"]).astype(np.float32)
    returns = reward_to_go(cat["rewards"], dones, gamma)
    # successor observation per step; the final row bootstraps from the
    # runner's post-rollout obs. At done steps next_obs is the next
    # episode's reset obs — consumers mask it with (1 - dones).
    next_obs = np.concatenate(
        [cat["obs"][1:], chunks[-1]["next_obs"][None]], axis=0)
    mask = cat["valid"].reshape(-1)
    writer.write({
        "obs": cat["obs"].reshape(t_len * n, -1)[mask],
        "actions": cat["actions"].reshape(
            t_len * n, *cat["actions"].shape[2:])[mask],
        "rewards": cat["rewards"].reshape(-1)[mask].astype(np.float32),
        # dones = terminated OR truncated (resets the reward-to-go);
        # terminateds alone gates value BOOTSTRAPPING — a time-limit
        # truncation is an ordinary state whose successor still has value
        "dones": dones.reshape(-1)[mask],
        "terminateds": cat["terminateds"].astype(
            np.float32).reshape(-1)[mask],
        "returns": returns.reshape(-1)[mask],
        "next_obs": next_obs.reshape(t_len * n, -1)[mask],
    })
    writer.flush()
    runner.stop()
    return writer


class BCLearner(JaxLearner):
    """Behavior cloning: maximize log-prob of dataset actions (reference
    rllib/algorithms/bc)."""

    def compute_loss(self, params, batch):
        from ray_tpu.rllib.learner import masked_mean

        mask = batch.get("loss_mask")
        out = self.module.forward_train(params, batch["obs"])
        logp, entropy = self.module.logp_entropy(out, batch["actions"])
        ent_coeff = self.config.get("entropy_coeff", 0.0)
        mean_logp = masked_mean(logp, mask)
        mean_ent = masked_mean(entropy, mask)
        loss = -(mean_logp + ent_coeff * mean_ent)
        return loss, {"bc_logp": mean_logp, "entropy": mean_ent}


def train_bc(dataset_path: str, module_spec: Dict[str, Any],
             *, lr: float = 1e-3, num_epochs: int = 5,
             minibatch_size: int = 256, seed: int = 0) -> BCLearner:
    """Offline BC training loop over recorded shards."""
    reader = OfflineReader(dataset_path)
    learner = BCLearner(module_spec, {"lr": lr, "num_devices": 1}, seed=seed)
    data = reader.read_all()
    batch = {"obs": data["obs"].astype(np.float32),
             "actions": data["actions"]}
    learner.update(batch, minibatch_size=minibatch_size,
                   num_epochs=num_epochs)
    return learner


class MARWILLearner(JaxLearner):
    """Monotonic Advantage Re-Weighted Imitation Learning.

    Reference: ``rllib/algorithms/marwil/marwil.py`` +
    ``marwil_torch_policy.py:47`` (loss). Policy loss is exponentially
    advantage-weighted log-likelihood ``-mean(exp(beta * adv / norm) *
    logp)`` with ``adv = returns - V(s)`` detached, plus the value head's
    ``0.5 * mean(adv^2)``; ``beta = 0`` degenerates to BC (+vf). One
    jax-pure deviation from the reference: the squared-advantage
    normalizer is the CURRENT minibatch's mean square (stop-grad) rather
    than a moving average carried across updates — the scanned
    multi-minibatch update has no host-side mutable stat, and the
    instant estimate plays the same scale-stabilizer role.
    """

    def compute_loss(self, params, batch):
        import jax
        import jax.numpy as jnp

        from ray_tpu.rllib.learner import masked_mean

        beta = self.config.get("beta", 1.0)
        vf_coeff = self.config.get("vf_coeff", 1.0)
        mask = batch.get("loss_mask")
        out = self.module.forward_train(params, batch["obs"])
        logp, entropy = self.module.logp_entropy(out, batch["actions"])
        v = out["vf_preds"]
        adv = batch["returns"] - v
        vf_loss = 0.5 * masked_mean(adv ** 2, mask)
        if beta:
            adv_sg = jax.lax.stop_gradient(adv)
            norm = jnp.sqrt(masked_mean(adv_sg ** 2, mask)) + 1e-8
            weights = jnp.exp(beta * adv_sg / norm)
            p_loss = -masked_mean(weights * logp, mask)
        else:
            p_loss = -masked_mean(logp, mask)
            vf_loss = jnp.zeros_like(vf_loss)  # reference: beta=0 -> pure BC
        loss = p_loss + vf_coeff * vf_loss
        return loss, {"policy_loss": p_loss, "vf_loss": vf_loss,
                      "mean_logp": masked_mean(logp, mask),
                      "entropy": masked_mean(entropy, mask)}


def train_marwil(dataset_path: str, module_spec: Dict[str, Any],
                 *, beta: float = 1.0, vf_coeff: float = 1.0,
                 lr: float = 1e-3, num_epochs: int = 5,
                 minibatch_size: int = 256, seed: int = 0) -> MARWILLearner:
    """Offline MARWIL training loop over recorded shards (which must carry
    ``returns`` — :func:`record_episodes` writes them)."""
    reader = OfflineReader(dataset_path)
    learner = MARWILLearner(
        module_spec, {"lr": lr, "beta": beta, "vf_coeff": vf_coeff,
                      "num_devices": 1}, seed=seed)
    data = reader.read_all()
    if "returns" not in data:
        raise ValueError(
            f"dataset at {dataset_path!r} has no 'returns' column; "
            "re-record with record_episodes (>= round 5) or add "
            "discounted reward-to-go")
    batch = {"obs": data["obs"].astype(np.float32),
             "actions": data["actions"],
             "returns": data["returns"].astype(np.float32)}
    learner.update(batch, minibatch_size=minibatch_size,
                   num_epochs=num_epochs)
    return learner
