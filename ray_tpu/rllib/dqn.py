"""DQN: off-policy Q-learning with replay + target network.

Role analog: ``rllib/algorithms/dqn/`` (new-stack DQN: replay buffer,
target net sync, optional double-Q and prioritized replay — both on by
default here, as in the reference's rainbow-lite defaults). Exploration is
Boltzmann: the env runner samples categorically over Q-logits, annealing
naturally as Q-value gaps grow (no epsilon schedule).
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.learner import JaxLearner, LearnerGroup
from ray_tpu.rllib.replay import PrioritizedReplayBuffer, ReplayBuffer


class DQNLearner(JaxLearner):
    """Q-network learner; params double as online net, target kept here."""

    def __init__(self, module_spec_dict, config=None, seed: int = 0):
        super().__init__(module_spec_dict, config, seed)
        import jax

        self.target_params = jax.tree.map(lambda x: x, self.params)
        self._steps = 0

    def compute_loss(self, params, batch):
        import jax.numpy as jnp

        cfg = self.config
        gamma = cfg.get("gamma", 0.99)
        double_q = cfg.get("double_q", True)

        # q-values come from the pi head (action_dim outputs)
        out = self.module.forward_train(params, batch["obs"])
        q = out["action_dist_inputs"]
        q_taken = jnp.take_along_axis(
            q, batch["actions"][..., None].astype(jnp.int32), axis=-1)[..., 0]

        next_out_target = self.module.forward_train(
            batch["target_params"], batch["next_obs"])
        q_next_target = next_out_target["action_dist_inputs"]
        if double_q:
            next_out_online = self.module.forward_train(
                params, batch["next_obs"])
            best = jnp.argmax(next_out_online["action_dist_inputs"], axis=-1)
            q_next = jnp.take_along_axis(
                q_next_target, best[..., None], axis=-1)[..., 0]
        else:
            q_next = q_next_target.max(axis=-1)
        target = batch["rewards"] + gamma * q_next * (
            1.0 - batch["dones"].astype(jnp.float32))
        td_error = q_taken - jnp.asarray(target)
        weights = batch.get("weights")
        if weights is None:
            loss = jnp.mean(td_error ** 2)
        else:
            loss = jnp.mean(weights * td_error ** 2)
        return loss, {"td_error_abs": jnp.abs(td_error).mean(),
                      "q_mean": q_taken.mean()}

    def update(self, batch, minibatch_size=None, num_epochs: int = 1):
        import jax

        batch = dict(batch)
        batch["target_params"] = self.target_params
        # single full-batch step per update (off-policy convention)
        self.params, self.opt_state, metrics = self._update_fn(
            self.params, self.opt_state, batch)
        self._steps += 1
        if self._steps % self.config.get("target_update_freq", 100) == 0:
            self.target_params = jax.tree.map(lambda x: x, self.params)
        return {k: float(jax.device_get(v)) for k, v in metrics.items()}

    def td_errors(self, batch) -> np.ndarray:
        import jax
        import jax.numpy as jnp

        out = self.module.forward_train(self.params, batch["obs"])
        q = np.asarray(out["action_dist_inputs"])
        q_taken = np.take_along_axis(
            q, batch["actions"][..., None].astype(np.int64), axis=-1)[..., 0]
        tgt = self.module.forward_train(self.target_params, batch["next_obs"])
        q_next = np.asarray(tgt["action_dist_inputs"]).max(axis=-1)
        target = batch["rewards"] + self.config.get("gamma", 0.99) * \
            q_next * (1.0 - batch["dones"].astype(np.float32))
        return np.abs(q_taken - target)


class DQNConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or DQN)
        self.lr = 5e-4
        self.buffer_size = 50_000
        self.prioritized_replay = True
        self.learning_starts = 500
        self.train_batch_size = 64
        self.target_update_freq = 100
        self.double_q = True
        self.updates_per_iteration = 16


class DQN(Algorithm):
    config_cls = DQNConfig

    def _make_learner_group(self):
        cfg = self.algo_config
        learner_cfg = {
            "lr": cfg.lr, "grad_clip": cfg.grad_clip, "gamma": cfg.gamma,
            "double_q": getattr(cfg, "double_q", True),
            "target_update_freq": getattr(cfg, "target_update_freq", 100),
        }
        # off-policy learners stay local: replay lives with the learner
        return LearnerGroup(DQNLearner, self.module_spec, learner_cfg,
                            num_learners=0, seed=cfg.seed)

    def _setup_algo(self):
        super()._setup_algo()
        cfg = self.algo_config
        if getattr(cfg, "prioritized_replay", True):
            self.replay = PrioritizedReplayBuffer(cfg.buffer_size,
                                                  seed=cfg.seed)
        else:
            self.replay = ReplayBuffer(cfg.buffer_size, seed=cfg.seed)
        self._env_steps = 0

    def training_step(self) -> Dict[str, Any]:
        cfg = self.algo_config
        batches = self._sample(cfg.rollout_fragment_length)
        for b in batches:
            t_len, n = b["rewards"].shape
            # Exploration comes from the runner's categorical sampling over
            # Q-logits (Boltzmann); the stored action must be exactly what
            # the env executed.
            # autoreset reset-step rows (valid=False) are not real
            # transitions — the env ignored the action; keep them out of
            # the buffer.
            mask = b.get("valid", np.ones((t_len, n), bool)).reshape(-1)
            transitions = {
                "obs": b["obs"].reshape(t_len * n, -1)[mask],
                "actions": b["actions"].reshape(t_len * n)[mask],
                "rewards": b["rewards"].reshape(-1)[mask],
                "next_obs": np.concatenate(
                    [b["obs"][1:].reshape((t_len - 1) * n, -1),
                     b["next_obs"]], axis=0)[mask],
                "dones": np.logical_or(b["terminateds"],
                                       b["truncateds"]).reshape(-1)[mask],
            }
            self.replay.add(transitions)
            # valid rows only, matching PPO/IMPALA's num_env_steps_sampled
            self._env_steps += int(mask.sum())

        metrics: Dict[str, Any] = {"buffer_size": len(self.replay)}
        if len(self.replay) >= cfg.learning_starts:
            learner: DQNLearner = self.learner_group._local
            for _ in range(cfg.updates_per_iteration):
                batch = self.replay.sample(cfg.train_batch_size)
                metrics.update(learner.update(batch))
                if isinstance(self.replay, PrioritizedReplayBuffer):
                    self.replay.update_priorities(
                        batch["batch_indexes"], learner.td_errors(batch))
        self._sync_runner_weights()
        self._iteration += 1
        metrics["num_env_steps_sampled"] = self._env_steps
        return metrics
