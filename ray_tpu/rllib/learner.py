"""JaxLearner + LearnerGroup: the gradient side of RL.

Role analog: ``rllib/core/learner/learner.py`` (optimizers/loss/update) and
``learner_group.py:69``; gradient sync matches the reference's DDP wrap
(``rllib/core/learner/torch/torch_learner.py:387-399``) semantics.

TPU-native design (BASELINE north star: "port LearnerGroup/TorchLearner
gradient sync to pjit-sharded JAX learners"):

- ONE learner process owns a device mesh: params/opt-state live replicated
  across the mesh, the batch shards over the ``dp`` axis, and the update is
  one jitted step whose gradient reduction is the psum XLA inserts for the
  global-mean loss. Scaling learners = widening the mesh, not spawning DDP
  ranks.
- MULTIPLE learner actors (CPU scaling / multi-host) synchronize with
  per-step gradient averaging — compute grads on each shard, average, apply
  the SAME update everywhere — which is numerically identical to one
  learner seeing the whole batch (NOT weight averaging after independent
  Adam steps, which diverges).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np


def masked_mean(x, mask):
    """Mean of ``x`` over rows where ``mask`` is 1. Padded rows (mask 0)
    contribute exactly zero to both numerator and denominator, so the
    result equals the unpadded mean (reference learners achieve this with
    per-row loss weights; rllib/core/learner/learner.py minibatch path)."""
    if mask is None:
        return x.mean()
    return (x * mask).sum() / mask.sum()


# -- gradient wire compression (EQuARX role for the object-store hop) -------
# Multi-learner sync ships grads driver<->learners through the object
# store; int8 blockwise quantization cuts those bytes 4x. Same scheme as
# ray_tpu.parallel.ops.quantized_psum, host-side numpy.

_Q8_BLOCK = 256


def quantize_grads(tree, block: int = _Q8_BLOCK):
    """Pytree of f32 arrays -> compact int8 payload (leaves, treedef kept)."""
    import jax

    leaves, treedef = jax.tree.flatten(tree)
    out = []
    for a in leaves:
        a = np.asarray(a, np.float32)
        flat = a.reshape(-1)
        pad = (-flat.size) % block
        if pad:
            flat = np.concatenate([flat, np.zeros(pad, np.float32)])
        blocks = flat.reshape(-1, block)
        scale = np.abs(blocks).max(axis=1, keepdims=True) / 127.0
        safe = np.where(scale == 0.0, 1.0, scale)
        q = np.clip(np.rint(blocks / safe), -127, 127).astype(np.int8)
        out.append((q, scale.astype(np.float32), a.shape))
    return {"__q8__": True, "leaves": out, "treedef": treedef}


def dequantize_grads(payload):
    import jax

    leaves = []
    for q, scale, shape in payload["leaves"]:
        flat = (q.astype(np.float32) * scale).reshape(-1)
        n = int(np.prod(shape)) if shape else 1
        leaves.append(flat[:n].reshape(shape))
    return jax.tree.unflatten(payload["treedef"], leaves)


def _is_q8(x) -> bool:
    return isinstance(x, dict) and x.get("__q8__") is True


class JaxLearner:
    """Owns module params + optimizer; ``update`` runs the jitted loss/grad
    step over the learner's device mesh. Subclasses implement
    ``compute_loss`` (pure function)."""

    def __init__(self, module_spec_dict: Dict[str, Any],
                 config: Optional[Dict[str, Any]] = None, seed: int = 0):
        import jax
        import optax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        self.config = dict(config or {})
        self._build_module(module_spec_dict)

        # Mesh over this process's devices, one "dp" axis: RL modules are
        # small, so params replicate and the batch shards — the grad psum
        # is inserted by XLA because the loss means over the global batch.
        n_dev = int(self.config.get("num_devices") or jax.device_count())
        devices = np.array(jax.devices()[:n_dev])
        self.mesh = Mesh(devices, axis_names=("dp",))
        self._replicated = NamedSharding(self.mesh, P())
        self._batch_sharding = NamedSharding(self.mesh, P("dp"))

        params = self.module.init(jax.random.PRNGKey(seed))
        self.params = jax.device_put(params, self._replicated)
        lr = self.config.get("lr", 3e-4)
        clip = self.config.get("grad_clip", 0.5)
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(clip),
            optax.adam(lr),
        )
        self.opt_state = jax.device_put(self.optimizer.init(self.params),
                                        self._replicated)
        from ray_tpu.util.device_plane import registered_jit

        self._update_fn = registered_jit(self._update_step,
                                         name="rllib::update",
                                         component="rllib")
        # scanned multi-step program. NOT donated: a transient axon
        # UNAVAILABLE mid-execute must leave self.params usable for the
        # retry (donation would invalidate the old buffers at dispatch),
        # and RL modules are small enough that double-buffering is free.
        self._update_steps_fn = registered_jit(self._update_steps,
                                               name="rllib::update_steps",
                                               component="rllib")
        self._grad_fn = registered_jit(self._grad_step,
                                       name="rllib::grad",
                                       component="rllib")
        self._apply_fn = registered_jit(self._apply_step,
                                        name="rllib::apply_grads",
                                        component="rllib")

    # -- override points --------------------------------------------------

    def _build_module(self, module_spec_dict: Dict[str, Any]) -> None:
        """Construct ``self.spec`` / ``self.module`` from the spec dict.
        Multi-agent learners override this to build a module PER policy
        (reference MultiAgentRLModule role)."""
        from ray_tpu.rllib.rl_module import RLModuleSpec

        self.spec = RLModuleSpec(**module_spec_dict)
        self.module = self.spec.build()

    def compute_loss(self, params, batch: Dict[str, Any]):
        """Return (loss, metrics_dict). Pure; jitted by the learner."""
        raise NotImplementedError

    # -- update machinery -------------------------------------------------

    def _update_step(self, params, opt_state, batch):
        import jax
        import optax

        (loss, metrics), grads = jax.value_and_grad(
            self.compute_loss, has_aux=True)(params, batch)
        updates, opt_state = self.optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        metrics = dict(metrics)
        metrics["total_loss"] = loss
        metrics["grad_norm"] = optax.global_norm(grads)
        return params, opt_state, metrics

    def _update_steps(self, params, opt_state, batch, plan, masks):
        """All minibatches of all epochs as ONE device program: lax.scan
        over the [n_steps, target] int32 minibatch ``plan``, gathering
        each step's rows from the once-transferred ``batch`` on device.

        One dispatch + one device_get per update() instead of one per
        minibatch — on the tunneled axon backend a per-minibatch
        device_get pays a tunnel round trip per step, which measured
        1.8 grad-steps/s in round 4 (BENCH_r04) vs 127/s on local CPU.
        Same treatment TrainLoopHelper.run_steps gives the train loop.
        Shipping indices (not gathered copies) keeps the transfer at 1x
        the batch bytes regardless of num_epochs."""
        import jax

        def body(carry, step):
            idx, mask = step
            p, o = carry
            mb = {k: v[idx] for k, v in batch.items()}
            mb["loss_mask"] = mask
            p, o, metrics = self._update_step(p, o, mb)
            return (p, o), metrics

        (params, opt_state), metrics = jax.lax.scan(
            body, (params, opt_state), (plan, masks))
        return params, opt_state, metrics

    def _grad_step(self, params, batch):
        import jax

        (loss, metrics), grads = jax.value_and_grad(
            self.compute_loss, has_aux=True)(params, batch)
        metrics = dict(metrics)
        metrics["total_loss"] = loss
        return grads, metrics

    def _apply_step(self, params, opt_state, grads):
        import optax

        updates, opt_state = self.optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state

    def _place_batch(self, batch):
        import jax

        with jax.set_mesh(self.mesh):
            return jax.tree.map(
                lambda v: jax.device_put(v, self._batch_sharding), batch)

    def _pad_to_devices(self, batch):
        """Pad the leading dim to a multiple of the mesh size (dp sharding
        needs equal shards) by repeating trailing rows, and attach a
        ``loss_mask`` (1 real / 0 padded). Losses take ``masked_mean`` so
        padded rows carry ZERO loss weight — the update is identical to the
        unpadded batch, not biased toward repeated rows. The mask is always
        present so jit sees one batch signature."""
        n_dev = self.mesh.devices.size
        n = len(next(iter(batch.values())))
        pad = (-n) % n_dev
        mask = np.ones(n + pad, dtype=np.float32)
        if pad == 0:
            return {**batch, "loss_mask": mask}
        mask[n:] = 0.0
        out = {k: np.concatenate([v, v[-pad:]], axis=0)
               for k, v in batch.items()}
        out["loss_mask"] = mask
        return out

    def update(self, batch: Dict[str, np.ndarray],
               minibatch_size: Optional[int] = None,
               num_epochs: int = 1) -> Dict[str, float]:
        """Multi-epoch minibatched update (reference Learner.update's
        minibatch loop), run as ONE scanned device program.

        The epoch×minibatch plan is assembled on the host as int32 row
        indices (each minibatch padded to a fixed row count with a zero
        loss_mask, so jit sees one signature); the batch itself is
        transferred ONCE and each step's rows are gathered on device.
        Metrics reported are the LAST minibatch's (same as the old
        per-step loop)."""
        import jax

        n = len(next(iter(batch.values())))
        minibatch_size = min(minibatch_size or n, n)
        n_dev = self.mesh.devices.size
        target = minibatch_size + ((-minibatch_size) % n_dev)
        rng = np.random.default_rng(0)
        rows, masks = [], []
        for _ in range(num_epochs):
            idx = rng.permutation(n)
            for start in range(0, n, minibatch_size):
                mb_idx = idx[start:start + minibatch_size]
                pad = target - len(mb_idx)
                mask = np.ones(target, np.float32)
                if pad:
                    mask[len(mb_idx):] = 0.0
                    mb_idx = np.concatenate(
                        [mb_idx, np.repeat(mb_idx[-1], pad)])
                rows.append(mb_idx)
                masks.append(mask)
        if not rows:  # num_epochs=0: nothing to do (old loop returned {})
            return {}
        plan = np.stack(rows).astype(np.int32)  # [n_steps, target]
        masks = np.stack(masks)
        # pad the batch's leading dim to the dp shard grid; padded rows
        # are never referenced (plan indices are all < n)
        placed = self._place_batch(self._pad_to_devices(batch))
        placed.pop("loss_mask", None)  # per-STEP masks ride the scan
        t0 = time.perf_counter()
        with jax.set_mesh(self.mesh):
            plan_d = jax.device_put(plan, self._replicated)
            masks_d = jax.device_put(masks, self._replicated)
            self.params, self.opt_state, metrics = self._update_steps_fn(
                self.params, self.opt_state, placed, plan_d, masks_d)
        got = jax.device_get(metrics)  # single transfer spanning all steps
        self._note_device_update(time.perf_counter() - t0, len(plan))
        return {k: float(np.asarray(v)[-1]) for k, v in got.items()}

    def _note_device_update(self, dt: float, n_steps: int) -> None:
        """Cost-model attribution for the scanned update: achieved
        FLOP/s from the registered program's static cost analysis and
        the wall time of dispatch→``device_get`` (the get spans every
        scanned step, so the window is sound even on the tunneled
        backend). The scan length is per-call (the epoch×minibatch
        plan), so per-step flops are derived here, not in the row."""
        try:
            from ray_tpu.util import device_plane

            flops = device_plane.program_flops_per_step(
                "rllib::update_steps")
            if flops and dt > 0:
                from ray_tpu.util import metric_defs as md

                md.get("rtpu_device_achieved_flops_per_s").set(
                    flops / dt, tags={"program": "rllib::update_steps"})
            from ray_tpu.util import tracing

            if tracing.tracing_enabled():
                end = time.time_ns()
                tracing.record_span(
                    "rllib::update", end - int(dt * 1e9), end,
                    {"program": "rllib::update_steps",
                     "steps": int(n_steps),
                     **({"flops": flops} if flops else {})})
        except Exception:
            pass

    # -- gradient-sync API (multi-learner DDP semantics) -------------------

    def compute_grads(self, batch: Dict[str, np.ndarray], compress=None):
        """Grads + metrics on this learner's shard (host pytree).

        ``compress="int8"`` returns the blockwise-quantized payload so the
        object-store hop back to the group driver ships 4x fewer bytes."""
        import jax

        mb = self._place_batch(self._pad_to_devices(batch))
        with jax.set_mesh(self.mesh):
            grads, metrics = self._grad_fn(self.params, mb)
        grads = jax.device_get(grads)
        if compress == "int8":
            grads = quantize_grads(grads)
        return (grads,
                {k: float(jax.device_get(v)) for k, v in metrics.items()})

    def apply_grads(self, grads) -> None:
        """Apply (already averaged) grads — every learner applies the SAME
        update, so states stay bit-identical across the group. Accepts the
        int8 payload from :func:`quantize_grads` transparently."""
        import jax

        if _is_q8(grads):
            grads = dequantize_grads(grads)
        grads = jax.device_put(grads, self._replicated)
        with jax.set_mesh(self.mesh):
            self.params, self.opt_state = self._apply_fn(
                self.params, self.opt_state, grads)

    # -- state ------------------------------------------------------------

    def get_weights(self):
        import jax

        return jax.device_get(self.params)

    def set_weights(self, params) -> None:
        import jax

        self.params = jax.device_put(params, self._replicated)

    def get_state(self) -> Dict[str, Any]:
        import jax

        return {"params": jax.device_get(self.params),
                "opt_state": jax.device_get(self.opt_state)}

    def set_state(self, state: Dict[str, Any]) -> None:
        import jax

        self.params = jax.device_put(state["params"], self._replicated)
        self.opt_state = jax.device_put(state["opt_state"],
                                        self._replicated)


class LearnerGroup:
    """Local or remote learner management (reference
    ``learner_group.py:69``; remote learners spawned like Train workers).

    Multi-learner updates use per-step gradient averaging (reference DDP
    semantics): shard the minibatch, gather grads, average, apply the same
    update on every learner — never weight-averaging after independent
    optimizer steps."""

    def __init__(self, learner_cls, module_spec_dict: Dict[str, Any],
                 config: Optional[Dict[str, Any]] = None,
                 num_learners: int = 0, seed: int = 0):
        # "int8" ships grads through the object store blockwise-quantized
        # (4x fewer bytes each way; error <= blockwise max_abs/127)
        self._compress = (config or {}).get("grad_compression")
        if self._compress not in (None, "int8"):
            raise ValueError(
                f"unknown grad_compression {self._compress!r}; "
                "expected None or 'int8'")
        self._remote = num_learners > 0
        if self._remote:
            import ray_tpu

            cls = ray_tpu.remote(learner_cls)
            # identical seed everywhere: gradient-sync keeps states
            # identical only if they START identical
            self._learners = [
                cls.options(num_cpus=1).remote(module_spec_dict, config,
                                               seed)
                for _ in range(num_learners)]
        else:
            self._local = learner_cls(module_spec_dict, config, seed)

    def update(self, batch: Dict[str, np.ndarray],
               minibatch_size: Optional[int] = None,
               num_epochs: int = 1) -> Dict[str, float]:
        if not self._remote:
            return self._local.update(batch, minibatch_size=minibatch_size,
                                      num_epochs=num_epochs)
        import jax
        import ray_tpu

        n_learners = len(self._learners)
        n = len(next(iter(batch.values())))
        minibatch_size = minibatch_size or n
        rng = np.random.default_rng(0)
        last_metrics: Dict[str, float] = {}
        for _ in range(num_epochs):
            idx = rng.permutation(n)
            for start in range(0, n, minibatch_size):
                mb_idx = idx[start:start + minibatch_size]
                mb = {k: v[mb_idx] for k, v in batch.items()}
                # shard the minibatch across learners on the leading dim;
                # near-even split, empty shards dropped (they would produce
                # NaN metrics and mis-scale the average)
                splits = np.array_split(np.arange(len(mb_idx)), n_learners)
                refs, weights = [], []
                for learner, rows in zip(self._learners, splits):
                    if len(rows) == 0:
                        continue
                    shard = {k: v[rows] for k, v in mb.items()}
                    refs.append(learner.compute_grads.remote(
                        shard, self._compress))
                    weights.append(float(len(rows)))
                outs = ray_tpu.get(refs)
                grads = [dequantize_grads(g) if _is_q8(g) else g
                         for g, _ in outs]
                metrics_list = [m for _, m in outs]
                # size-weighted average of per-shard MEAN grads == the
                # global-batch mean gradient (the docstring's equivalence
                # claim holds for uneven shards too)
                w = np.asarray(weights) / np.sum(weights)
                avg = jax.tree.map(
                    lambda *gs: np.tensordot(w, np.stack(gs), axes=1),
                    *grads)
                if self._compress == "int8":
                    avg = quantize_grads(avg)
                ray_tpu.get([l.apply_grads.remote(avg)
                             for l in self._learners])
                last_metrics = {
                    k: float(np.sum([wi * m[k] for wi, m in
                                     zip(w, metrics_list)]))
                    for k in metrics_list[0]}
        return last_metrics

    def get_weights(self):
        if not self._remote:
            return self._local.get_weights()
        import ray_tpu

        return ray_tpu.get(self._learners[0].get_weights.remote())

    def get_state(self):
        if not self._remote:
            return self._local.get_state()
        import ray_tpu

        return ray_tpu.get(self._learners[0].get_state.remote())

    def set_state(self, state):
        if not self._remote:
            return self._local.set_state(state)
        import ray_tpu

        ray_tpu.get([l.set_state.remote(state) for l in self._learners])