"""JaxLearner + LearnerGroup: the gradient side of RL.

Role analog: ``rllib/core/learner/learner.py`` (optimizers/loss/update) and
``learner_group.py:69``. TPU-native difference (BASELINE north star: "port
LearnerGroup/TorchLearner gradient sync to pjit-sharded JAX learners"): one
learner process owns a device mesh and the update is one jitted step;
scaling learners = widening the mesh's dp axis, not spawning DDP ranks —
gradient sync is a psum XLA inserts, not an explicit allreduce.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np


class JaxLearner:
    """Owns module params + optimizer; ``update`` runs the jitted loss/grad
    step. Subclasses implement ``compute_loss`` (pure function)."""

    def __init__(self, module_spec_dict: Dict[str, Any],
                 config: Optional[Dict[str, Any]] = None, seed: int = 0):
        import jax
        import optax

        from ray_tpu.rllib.rl_module import RLModuleSpec

        self.config = dict(config or {})
        self.spec = RLModuleSpec(**module_spec_dict)
        self.module = self.spec.build()
        self.params = self.module.init(jax.random.PRNGKey(seed))
        lr = self.config.get("lr", 3e-4)
        clip = self.config.get("grad_clip", 0.5)
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(clip),
            optax.adam(lr),
        )
        self.opt_state = self.optimizer.init(self.params)
        self._update_fn = jax.jit(self._update_step)

    # -- override point ---------------------------------------------------

    def compute_loss(self, params, batch: Dict[str, Any]):
        """Return (loss, metrics_dict). Pure; jitted by the learner."""
        raise NotImplementedError

    # -- update machinery -------------------------------------------------

    def _update_step(self, params, opt_state, batch):
        import jax
        import optax

        (loss, metrics), grads = jax.value_and_grad(
            self.compute_loss, has_aux=True)(params, batch)
        updates, opt_state = self.optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        metrics = dict(metrics)
        metrics["total_loss"] = loss
        metrics["grad_norm"] = optax.global_norm(grads)
        return params, opt_state, metrics

    def update(self, batch: Dict[str, np.ndarray],
               minibatch_size: Optional[int] = None,
               num_epochs: int = 1) -> Dict[str, float]:
        """Multi-epoch minibatched update (reference Learner.update's
        minibatch loop)."""
        import jax

        n = len(next(iter(batch.values())))
        minibatch_size = minibatch_size or n
        rng = np.random.default_rng(0)
        last_metrics: Dict[str, float] = {}
        for _ in range(num_epochs):
            idx = rng.permutation(n)
            for start in range(0, n, minibatch_size):
                mb_idx = idx[start:start + minibatch_size]
                mb = {k: v[mb_idx] for k, v in batch.items()}
                self.params, self.opt_state, metrics = self._update_fn(
                    self.params, self.opt_state, mb)
                last_metrics = {k: float(jax.device_get(v))
                                for k, v in metrics.items()}
        return last_metrics

    def get_weights(self):
        import jax

        return jax.device_get(self.params)

    def set_weights(self, params) -> None:
        self.params = params

    def get_state(self) -> Dict[str, Any]:
        import jax

        return {"params": jax.device_get(self.params),
                "opt_state": jax.device_get(self.opt_state)}

    def set_state(self, state: Dict[str, Any]) -> None:
        self.params = state["params"]
        self.opt_state = state["opt_state"]


class LearnerGroup:
    """Local or remote learner management (reference
    ``learner_group.py:69``; remote learners spawned like Train workers)."""

    def __init__(self, learner_cls, module_spec_dict: Dict[str, Any],
                 config: Optional[Dict[str, Any]] = None,
                 num_learners: int = 0, seed: int = 0):
        self._remote = num_learners > 0
        if self._remote:
            import ray_tpu

            cls = ray_tpu.remote(learner_cls)
            self._learners = [
                cls.options(num_cpus=1).remote(module_spec_dict, config,
                                               seed + i)
                for i in range(num_learners)]
        else:
            self._local = learner_cls(module_spec_dict, config, seed)

    def update(self, batch: Dict[str, np.ndarray], **kw) -> Dict[str, float]:
        if not self._remote:
            return self._local.update(batch, **kw)
        import ray_tpu

        # shard batch across learners on the leading dim (dp semantics);
        # each learner updates on its shard, then weights average.
        n = len(self._learners)
        size = len(next(iter(batch.values()))) // n
        refs = []
        for i, learner in enumerate(self._learners):
            shard = {k: v[i * size:(i + 1) * size] for k, v in batch.items()}
            refs.append(learner.update.remote(shard, **kw))
        metrics = ray_tpu.get(refs)
        self._sync_weights()
        out = {}
        for k in metrics[0]:
            out[k] = float(np.mean([m[k] for m in metrics]))
        return out

    def _sync_weights(self):
        """Average learner weights (data-parallel consensus). With one
        learner on a multi-chip mesh this is a no-op — XLA already psums
        grads inside the jitted step."""
        import jax
        import ray_tpu

        if len(self._learners) == 1:
            return
        weights = ray_tpu.get([l.get_weights.remote()
                               for l in self._learners])
        avg = jax.tree.map(lambda *ws: np.mean(np.stack(ws), axis=0),
                           *weights)
        ray_tpu.get([l.set_weights.remote(avg) for l in self._learners])

    def get_weights(self):
        if not self._remote:
            return self._local.get_weights()
        import ray_tpu

        return ray_tpu.get(self._learners[0].get_weights.remote())

    def get_state(self):
        if not self._remote:
            return self._local.get_state()
        import ray_tpu

        return ray_tpu.get(self._learners[0].get_state.remote())

    def set_state(self, state):
        if not self._remote:
            return self._local.set_state(state)
        import ray_tpu

        ray_tpu.get([l.set_state.remote(state) for l in self._learners])
