"""SAC: soft actor-critic for continuous control.

Role analog: ``rllib/algorithms/sac/`` (new API stack). Jax-native pieces:
a tanh-squashed diagonal-gaussian actor, twin Q critics with polyak-averaged
targets, and a learned entropy temperature — all one jitted update over the
learner mesh (the reference splits these across three torch optimizers).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Tuple

import numpy as np

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.rl_module import _mlp_apply, _mlp_init


@dataclass(frozen=True)
class SACModuleSpec:
    observation_dim: int
    action_dim: int
    hidden: Tuple[int, ...] = (256, 256)
    activation: str = "relu"
    # SAC is continuous-control; the env runner consults this for action
    # shaping (not an init field: frozen dataclass class-level constant)
    discrete = False

    def build(self) -> "SACModule":
        return SACModule(self)


LOG_STD_MIN, LOG_STD_MAX = -20.0, 2.0


class SACModule:
    """Actor (mean/log_std heads) + twin critics over (obs, action)."""

    def __init__(self, spec: SACModuleSpec):
        self.spec = spec

    def init(self, rng):
        import jax

        k_pi, k_q1, k_q2 = jax.random.split(rng, 3)
        s = self.spec
        return {
            "pi": _mlp_init(k_pi, (s.observation_dim, *s.hidden,
                                   2 * s.action_dim)),
            "q1": _mlp_init(k_q1, (s.observation_dim + s.action_dim,
                                   *s.hidden, 1)),
            "q2": _mlp_init(k_q2, (s.observation_dim + s.action_dim,
                                   *s.hidden, 1)),
        }

    def actor(self, params, obs):
        import jax.numpy as jnp

        out = _mlp_apply(params["pi"], obs, self.spec.activation)
        mean, log_std = jnp.split(out, 2, axis=-1)
        return mean, jnp.clip(log_std, LOG_STD_MIN, LOG_STD_MAX)

    def sample_action(self, params, obs, rng):
        """Tanh-squashed gaussian sample with the change-of-variables
        log-prob correction."""
        import jax
        import jax.numpy as jnp

        mean, log_std = self.actor(params, obs)
        std = jnp.exp(log_std)
        eps = jax.random.normal(rng, mean.shape)
        pre = mean + std * eps
        act = jnp.tanh(pre)
        logp = (-0.5 * (eps ** 2 + 2 * log_std + np.log(2 * np.pi))).sum(-1)
        logp -= (2 * (np.log(2.0) - pre - jax.nn.softplus(-2 * pre))).sum(-1)
        return act, logp

    def q_values(self, params, obs, act):
        import jax.numpy as jnp

        x = jnp.concatenate([obs, act], axis=-1)
        q1 = _mlp_apply(params["q1"], x, self.spec.activation)[..., 0]
        q2 = _mlp_apply(params["q2"], x, self.spec.activation)[..., 0]
        return q1, q2


class SACRolloutModule:
    """Runner-facing adapter: SAC actor behind the generic rollout module
    surface (``forward_exploration``/``forward_inference``)."""

    # actions are tanh-squashed into [-1, 1]; the env runner affinely maps
    # them to the env's action bounds (reference unsquash_action behavior)
    squashed = True

    def __init__(self, spec: SACModuleSpec):
        self.spec = spec
        self._mod = SACModule(spec)

    def init(self, rng):
        return self._mod.init(rng)

    def forward_exploration(self, params, obs, rng):
        import jax.numpy as jnp

        act, logp = self._mod.sample_action(params, obs, rng)
        return {"actions": act, "action_logp": logp,
                "vf_preds": jnp.zeros(act.shape[:-1])}

    def forward_inference(self, params, obs):
        import jax.numpy as jnp

        mean, _ = self._mod.actor(params, obs)
        return {"actions": jnp.tanh(mean)}

    def forward_train(self, params, obs):
        return self.forward_inference(params, obs)


class SACLearner:
    """One jitted SAC update: critic TD step, actor step, alpha step,
    polyak target update."""

    def __init__(self, module_spec_dict: Dict[str, Any],
                 config: Dict[str, Any] = None, seed: int = 0):
        import jax
        import jax.numpy as jnp
        import optax

        self.config = dict(config or {})
        self.spec = SACModuleSpec(**module_spec_dict)
        self.module = SACModule(self.spec)
        self.params = self.module.init(jax.random.PRNGKey(seed))
        self.target_params = jax.tree.map(lambda x: x, self.params)
        self.log_alpha = jnp.zeros(())
        lr = self.config.get("lr", 3e-4)
        self.optimizer = optax.adam(lr)
        self.alpha_opt = optax.adam(lr)
        self.opt_state = self.optimizer.init(self.params)
        self.alpha_state = self.alpha_opt.init(self.log_alpha)
        self._rng = jax.random.PRNGKey(seed + 1)
        from ray_tpu.util.device_plane import registered_jit

        self._update_fn = registered_jit(self._update_step,
                                         name="rllib::sac_update",
                                         component="rllib")

    def _update_step(self, params, target_params, log_alpha, opt_state,
                     alpha_state, batch, rng):
        import jax
        import jax.numpy as jnp
        import optax

        cfg = self.config
        gamma = cfg.get("gamma", 0.99)
        tau = cfg.get("tau", 0.005)
        target_entropy = cfg.get("target_entropy",
                                 -float(self.spec.action_dim))
        alpha = jnp.exp(log_alpha)
        k1, k2 = jax.random.split(rng)

        # -- critic target (no grad) --
        next_act, next_logp = self.module.sample_action(
            params, batch["next_obs"], k1)
        tq1, tq2 = self.module.q_values(target_params, batch["next_obs"],
                                        next_act)
        target_q = batch["rewards"] + gamma * (1.0 - batch["dones"]) * (
            jnp.minimum(tq1, tq2) - alpha * next_logp)
        target_q = jax.lax.stop_gradient(target_q)

        def critic_actor_loss(p):
            q1, q2 = self.module.q_values(p, batch["obs"], batch["actions"])
            critic_loss = ((q1 - target_q) ** 2 + (q2 - target_q) ** 2).mean()
            act, logp = self.module.sample_action(p, batch["obs"], k2)
            aq1, aq2 = self.module.q_values(jax.lax.stop_gradient(p),
                                            batch["obs"], act)
            actor_loss = (alpha * logp - jnp.minimum(aq1, aq2)).mean()
            return critic_loss + actor_loss, (critic_loss, actor_loss, logp)

        (loss, (c_loss, a_loss, logp)), grads = jax.value_and_grad(
            critic_actor_loss, has_aux=True)(params)
        updates, opt_state = self.optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)

        # -- temperature --
        def alpha_loss_fn(la):
            return -(jnp.exp(la) * jax.lax.stop_gradient(
                logp + target_entropy)).mean()

        a_grad = jax.grad(alpha_loss_fn)(log_alpha)
        a_updates, alpha_state = self.alpha_opt.update(a_grad, alpha_state)
        log_alpha = optax.apply_updates(log_alpha, a_updates)

        # -- polyak target update --
        target_params = jax.tree.map(
            lambda t, o: (1 - tau) * t + tau * o, target_params, params)
        metrics = {"critic_loss": c_loss, "actor_loss": a_loss,
                   "alpha": jnp.exp(log_alpha),
                   "entropy": -logp.mean()}
        return params, target_params, log_alpha, opt_state, alpha_state, metrics

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        import jax

        self._rng, key = jax.random.split(self._rng)
        (self.params, self.target_params, self.log_alpha, self.opt_state,
         self.alpha_state, metrics) = self._update_fn(
            self.params, self.target_params, self.log_alpha,
            self.opt_state, self.alpha_state, batch, key)
        return {k: float(jax.device_get(v)) for k, v in metrics.items()}

    def get_state(self):
        import jax

        return {k: jax.device_get(getattr(self, k)) for k in
                ("params", "target_params", "log_alpha", "opt_state",
                 "alpha_state")}

    def set_state(self, state):
        for k, v in state.items():
            setattr(self, k, v)


class SACConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or SAC)
        self.lr = 3e-4
        self.gamma = 0.99
        self.tau = 0.005
        self.buffer_size = 100_000
        self.train_batch_size = 256
        self.learning_starts = 1000
        self.updates_per_iteration = 16


class SAC(Algorithm):
    config_cls = SACConfig

    def _transform_module_spec(self, spec_dict):
        if spec_dict.get("discrete", False):
            raise ValueError("SAC supports continuous action spaces only")
        return {"kind": "sac",
                "observation_dim": spec_dict["observation_dim"],
                "action_dim": spec_dict["action_dim"]}

    def _make_learner_group(self):
        # SAC owns its learner directly (three-part state doesn't fit the
        # generic param/opt pair the shared LearnerGroup syncs); replay
        # state rides along since this hook runs during algorithm setup
        # (Trainable.setup is a no-op here — see Algorithm.__init__)
        from ray_tpu.rllib.replay import ReplayBuffer

        cfg = self.algo_config
        spec = dict(self.module_spec)
        self._sac_learner = SACLearner(
            {"observation_dim": spec["observation_dim"],
             "action_dim": spec["action_dim"]},
            {"lr": cfg.lr, "gamma": cfg.gamma, "tau": cfg.tau},
            seed=cfg.seed or 0)
        self.replay = ReplayBuffer(cfg.buffer_size, seed=cfg.seed or 0)
        self._env_steps = 0
        return _SacLearnerGroupShim(self._sac_learner, self.module_spec)

    def training_step(self) -> Dict[str, Any]:
        cfg = self.algo_config
        batches = self._sample(cfg.rollout_fragment_length)
        for b in batches:
            t_len, n = b["rewards"].shape
            mask = b.get("valid", np.ones((t_len, n), bool)).reshape(-1)
            self.replay.add({
                "obs": b["obs"].reshape(t_len * n, -1)[mask],
                "actions": b["actions"].reshape(
                    t_len * n, *b["actions"].shape[2:])[mask],
                "rewards": b["rewards"].reshape(-1)[mask],
                "next_obs": np.concatenate(
                    [b["obs"][1:].reshape((t_len - 1) * n, -1),
                     b["next_obs"]], axis=0)[mask],
                # SAC bootstraps through truncation, cuts at termination
                "dones": b["terminateds"].reshape(-1)[mask].astype(
                    np.float32),
            })
            self._env_steps += int(mask.sum())

        metrics: Dict[str, Any] = {"buffer_size": len(self.replay)}
        if len(self.replay) >= cfg.learning_starts:
            for _ in range(cfg.updates_per_iteration):
                batch = self.replay.sample(cfg.train_batch_size)
                metrics.update(self._sac_learner.update(batch))
        self._sync_runner_weights()
        self._iteration += 1
        metrics["num_env_steps_sampled"] = self._env_steps
        return metrics


class _SacLearnerGroupShim:
    """Adapts SACLearner to the Algorithm's LearnerGroup surface (weights
    for env runners, checkpoint state)."""

    def __init__(self, learner: SACLearner, module_spec):
        self._learner = learner
        self._module_spec = module_spec

    def get_weights(self):
        import jax

        # env runners run the generic actor-critic module; hand them the
        # SAC actor packed into that layout (mean head only for rollouts)
        return jax.device_get(self._learner.params)

    def get_state(self):
        return self._learner.get_state()

    def set_state(self, state):
        self._learner.set_state(state)
