"""Multi-agent RL: env API, env runner, MARL module, multi-agent PPO.

Role analogs in the reference:

- ``rllib/env/multi_agent_env.py`` — the :class:`MultiAgentEnv` dict API
  (``reset -> (obs_dict, info)``, ``step(actions_dict) -> (obs, rew, term,
  trunc, info)`` with the ``"__all__"`` termination key);
- ``rllib/core/rl_module/marl_module.py`` — :class:`MultiAgentRLModuleSpec`
  / :class:`MultiAgentRLModule` (one sub-module per policy id, params =
  ``{module_id: sub_params}``);
- ``rllib/env/multi_agent_env_runner.py`` — :class:`MultiAgentEnvRunner`
  (maps agents to modules via the policy-mapping fn, batches per module);
- multi-agent PPO = reference PPO's multi-agent path (per-module loss sum,
  ``compute_loss_for_module`` over the shared GAE pipeline).

TPU-native stance: identical to the single-agent stack — sampling on CPU
actors, ONE jitted update over all policy modules at once (the summed loss
differentiates through every sub-module in a single XLA program, instead
of the reference's per-policy optimizer loop).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.rllib.algorithm import Algorithm
from ray_tpu.rllib.learner import JaxLearner, LearnerGroup
from ray_tpu.rllib.ppo import PPOConfig, compute_gae, ppo_loss


# ---------------------------------------------------------------------------
# Env API
# ---------------------------------------------------------------------------

class MultiAgentEnv:
    """Dict-keyed multi-agent env (reference MultiAgentEnv API).

    Subclasses define ``agents`` (tuple of agent ids), ``observation_dim``
    and ``action_dim`` per agent (via :meth:`spaces`), and implement
    :meth:`reset` / :meth:`step`. All agents act every step (simultaneous
    game); ``step`` returns per-agent dicts plus ``terminateds["__all__"]``.
    """

    agents: Tuple[str, ...] = ()

    def spaces(self, agent_id: str) -> Dict[str, Any]:
        """{"observation_dim": int, "action_dim": int, "discrete": bool}"""
        raise NotImplementedError

    def reset(self, seed: Optional[int] = None
              ) -> Tuple[Dict[str, np.ndarray], Dict]:
        raise NotImplementedError

    def step(self, actions: Dict[str, Any]):
        raise NotImplementedError


class DebugCooperativeMatch(MultiAgentEnv):
    """Toy 2-agent contextual game for tests/examples: each agent sees a
    one-hot context and earns +1 for choosing the matching action, with a
    small shared bonus when BOTH match (cooperative coupling, so the task
    is multi-agent, not two independent bandits)."""

    agents = ("agent_0", "agent_1")

    def __init__(self, n_contexts: int = 4, episode_len: int = 16,
                 seed: int = 0):
        self.n = n_contexts
        self.episode_len = episode_len
        self._rng = np.random.default_rng(seed)
        self._t = 0
        self._ctx = {}

    def spaces(self, agent_id: str) -> Dict[str, Any]:
        return {"observation_dim": self.n, "action_dim": self.n,
                "discrete": True}

    def _obs(self) -> Dict[str, np.ndarray]:
        out = {}
        for a in self.agents:
            o = np.zeros(self.n, np.float32)
            o[self._ctx[a]] = 1.0
            out[a] = o
        return out

    def reset(self, seed: Optional[int] = None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._t = 0
        self._ctx = {a: int(self._rng.integers(self.n)) for a in self.agents}
        return self._obs(), {}

    def step(self, actions: Dict[str, Any]):
        hits = {a: float(int(actions[a]) == self._ctx[a])
                for a in self.agents}
        both = all(hits.values())
        rewards = {a: hits[a] + (0.5 if both else 0.0) for a in self.agents}
        self._t += 1
        done = self._t >= self.episode_len
        self._ctx = {a: int(self._rng.integers(self.n)) for a in self.agents}
        obs = self._obs()
        terminateds = {a: done for a in self.agents}
        terminateds["__all__"] = done
        truncateds = {a: False for a in self.agents}
        truncateds["__all__"] = False
        return obs, rewards, terminateds, truncateds, {}


# ---------------------------------------------------------------------------
# MARL module
# ---------------------------------------------------------------------------

class MultiAgentRLModuleSpec:
    """``module_specs``: module_id -> RLModuleSpec kwargs dict
    (reference ``MultiAgentRLModuleSpec`` role)."""

    def __init__(self, module_specs: Dict[str, Dict[str, Any]]):
        self.module_specs = dict(module_specs)

    def build(self) -> "MultiAgentRLModule":
        return MultiAgentRLModule(self)

    def to_dict(self) -> Dict[str, Any]:
        return {"multi_agent": True, "module_specs": self.module_specs}


class MultiAgentRLModule:
    """One sub-module per policy id; params = {module_id: sub_params}."""

    def __init__(self, spec: MultiAgentRLModuleSpec):
        from ray_tpu.rllib.rl_module import RLModuleSpec

        self.spec = spec
        self.modules = {
            mid: RLModuleSpec(**kw).build()
            for mid, kw in spec.module_specs.items()
        }

    def __getitem__(self, module_id: str):
        return self.modules[module_id]

    def init(self, rng) -> Dict[str, Any]:
        import jax

        keys = jax.random.split(rng, len(self.modules))
        return {mid: m.init(k)
                for (mid, m), k in zip(sorted(self.modules.items()), keys)}

    def forward_train(self, params, obs_by_module: Dict[str, Any]):
        return {mid: self.modules[mid].forward_train(params[mid], obs)
                for mid, obs in obs_by_module.items()}


# ---------------------------------------------------------------------------
# Env runner
# ---------------------------------------------------------------------------

class MultiAgentEnvRunner:
    """Steps one multi-agent env; emits per-MODULE batches of [T, A_m]
    arrays (A_m = number of agents mapped to that module). Reference:
    ``multi_agent_env_runner.py`` + agent-to-module mapping fn."""

    def __init__(self, env_maker: Callable[..., MultiAgentEnv],
                 module_specs: Optional[Dict[str, Dict[str, Any]]] = None,
                 agent_to_module: Optional[Callable[[str], str]] = None,
                 seed: int = 0, env_config: Optional[Dict[str, Any]] = None):
        import jax

        self.env = env_maker(**(env_config or {}))
        self.agents = tuple(self.env.agents)
        self.a2m = agent_to_module or (lambda aid: aid)
        # module id -> its agents, in stable order
        self.module_agents: Dict[str, List[str]] = {}
        for a in self.agents:
            self.module_agents.setdefault(self.a2m(a), []).append(a)
        if module_specs is None:
            module_specs = {}
            for mid, ags in self.module_agents.items():
                module_specs[mid] = dict(self.env.spaces(ags[0]),
                                         hidden=(32, 32))
        self.ma_spec = MultiAgentRLModuleSpec(module_specs)
        self.module = self.ma_spec.build()
        self.params = self.module.init(jax.random.PRNGKey(seed))
        self._rng = jax.random.PRNGKey(seed + 1)
        from ray_tpu.util.device_plane import registered_jit

        self._explore = {
            mid: registered_jit(m.forward_exploration,
                                name=f"rllib::forward_exploration[{mid}]",
                                component="rllib")
            for mid, m in self.module.modules.items()}
        self._obs, _ = self.env.reset(seed=seed)
        self._ep_return = 0.0
        self._completed: List[float] = []

    def get_spec(self) -> Dict[str, Any]:
        return self.ma_spec.to_dict()

    def set_weights(self, params) -> None:
        self.params = params

    def sample(self, num_steps: int = 200) -> Dict[str, Dict[str, np.ndarray]]:
        import jax

        cols: Dict[str, Dict[str, List]] = {
            mid: {k: [] for k in ("obs", "actions", "action_logp",
                                  "vf_preds", "rewards", "terminateds",
                                  "truncateds")}
            for mid in self.module_agents}
        for _ in range(num_steps):
            actions_env: Dict[str, Any] = {}
            per_mid_step: Dict[str, Dict[str, np.ndarray]] = {}
            for mid, ags in self.module_agents.items():
                obs = np.stack([self._obs[a] for a in ags])
                self._rng, sub = jax.random.split(self._rng)
                out = self._explore[mid](self.params[mid], obs, sub)
                acts = np.asarray(out["actions"])
                per_mid_step[mid] = {
                    "obs": obs,
                    "actions": acts,
                    "action_logp": np.asarray(out["action_logp"]),
                    "vf_preds": np.asarray(out["vf_preds"]),
                }
                for a, act in zip(ags, acts):
                    actions_env[a] = act
            obs, rew, term, trunc, _ = self.env.step(actions_env)
            self._ep_return += float(sum(rew.values()))
            for mid, ags in self.module_agents.items():
                c = cols[mid]
                s = per_mid_step[mid]
                c["obs"].append(s["obs"])
                c["actions"].append(s["actions"])
                c["action_logp"].append(s["action_logp"])
                c["vf_preds"].append(s["vf_preds"])
                c["rewards"].append(
                    np.asarray([rew[a] for a in ags], np.float32))
                c["terminateds"].append(
                    np.asarray([term.get(a, False) for a in ags]))
                c["truncateds"].append(
                    np.asarray([trunc.get(a, False) for a in ags]))
            if term.get("__all__") or trunc.get("__all__"):
                self._completed.append(self._ep_return)
                self._ep_return = 0.0
                obs, _ = self.env.reset()
            self._obs = obs
        out: Dict[str, Dict[str, np.ndarray]] = {}
        for mid, ags in self.module_agents.items():
            b = {k: np.stack(v) for k, v in cols[mid].items()}
            b["next_obs"] = np.stack([self._obs[a] for a in ags])
            out[mid] = b
        return out

    def get_metrics(self) -> Dict[str, Any]:
        if not self._completed:
            return {"episode_return_mean": 0.0, "num_episodes": 0}
        recent = self._completed[-100:]
        return {"episode_return_mean": float(np.mean(recent)),
                "num_episodes": len(self._completed)}

    def ping(self) -> bool:
        return True

    def stop(self) -> None:
        pass


# ---------------------------------------------------------------------------
# Learner + algorithm
# ---------------------------------------------------------------------------

class MultiAgentPPOLearner(JaxLearner):
    """Sums the PPO loss over every policy module in ONE jitted update —
    all sub-modules differentiate in a single XLA program (the reference
    loops per-policy optimizers; one fused program is the TPU-native
    shape)."""

    def _build_module(self, module_spec_dict: Dict[str, Any]) -> None:
        self.spec = MultiAgentRLModuleSpec(module_spec_dict["module_specs"])
        self.module = self.spec.build()

    def compute_loss(self, params, batch):
        total = None
        metrics: Dict[str, Any] = {}
        for mid in sorted(self.module.modules):
            loss, m = ppo_loss(self.module[mid], self.config,
                               params[mid], batch[mid])
            total = loss if total is None else total + loss
            for k, v in m.items():
                metrics[f"{mid}/{k}"] = v
        return total, metrics

    def _pad_to_devices(self, batch):
        return {mid: super(MultiAgentPPOLearner, self)._pad_to_devices(b)
                for mid, b in batch.items()}

    def update(self, batch: Dict[str, Dict[str, np.ndarray]],
               minibatch_size: Optional[int] = None,
               num_epochs: int = 1) -> Dict[str, float]:
        import jax

        rng = np.random.default_rng(0)
        ns = {mid: len(next(iter(b.values()))) for mid, b in batch.items()}
        n_max = max(ns.values())
        mb = minibatch_size or n_max
        num_mb = max(1, -(-n_max // mb))
        last: Dict[str, float] = {}
        for _ in range(num_epochs):
            perms = {mid: rng.permutation(n) for mid, n in ns.items()}
            for i in range(num_mb):
                shard = {}
                for mid, b in batch.items():
                    # fixed per-module minibatch size (wraparound slicing)
                    # so jit sees ONE batch signature across steps
                    size = min(mb, ns[mid])
                    idx = np.take(perms[mid],
                                  np.arange(i * size, (i + 1) * size),
                                  mode="wrap")
                    shard[mid] = {k: v[idx] for k, v in b.items()}
                placed = self._place_batch(self._pad_to_devices(shard))
                with jax.set_mesh(self.mesh):
                    self.params, self.opt_state, metrics = self._update_fn(
                        self.params, self.opt_state, placed)
                last = {k: float(jax.device_get(v))
                        for k, v in metrics.items()}
        return last


class MultiAgentPPOConfig(PPOConfig):
    """PPO config with the reference's ``.multi_agent(policies=...,
    policy_mapping_fn=...)`` surface. ``environment`` takes the env MAKER
    (a callable), not a gym id."""

    def __init__(self, algo_class=None):
        super().__init__(algo_class or MultiAgentPPO)
        self.env_maker: Optional[Callable] = None
        self.policies: Optional[Dict[str, Optional[Dict]]] = None
        self.policy_mapping_fn: Optional[Callable[[str], str]] = None

    def environment(self, env, *, env_config: Optional[Dict] = None):
        if callable(env):
            self.env_maker = env
            if env_config:
                self.env_config = env_config
            return self
        return super().environment(env, env_config=env_config)

    def multi_agent(self, *, policies: Optional[Dict] = None,
                    policy_mapping_fn: Optional[Callable] = None
                    ) -> "MultiAgentPPOConfig":
        if policies is not None:
            self.policies = policies
        if policy_mapping_fn is not None:
            self.policy_mapping_fn = policy_mapping_fn
        return self


class MultiAgentPPO(Algorithm):
    config_cls = MultiAgentPPOConfig

    def _setup_algo(self):
        cfg = self.algo_config
        assert cfg.env_maker is not None, \
            "MultiAgentPPO needs .environment(env_maker)"
        a2m = cfg.policy_mapping_fn or (lambda aid: aid)
        probe = MultiAgentEnvRunner(cfg.env_maker, None, a2m, cfg.seed,
                                    cfg.env_config)
        specs = probe.get_spec()["module_specs"]
        if cfg.policies:
            for mid, override in cfg.policies.items():
                if override:
                    specs.setdefault(mid, {}).update(override)
        self.module_spec = {"multi_agent": True, "module_specs": specs}
        self._a2m = a2m
        probe.stop()

        if cfg.num_env_runners > 0:
            import ray_tpu

            runner_cls = ray_tpu.remote(MultiAgentEnvRunner)

            def make_runner(i: int):
                return runner_cls.options(num_cpus=1).remote(
                    cfg.env_maker, specs, a2m,
                    cfg.seed + i * 1000 + 1, cfg.env_config)

            from ray_tpu.rllib.actor_manager import FaultTolerantActorManager

            self.env_runner_group = FaultTolerantActorManager(
                make_runner, cfg.num_env_runners)
            self.local_runner = None
        else:
            self.env_runner_group = None
            self.local_runner = MultiAgentEnvRunner(
                cfg.env_maker, specs, a2m, cfg.seed + 1, cfg.env_config)

        self.learner_group = self._make_learner_group()
        self._iteration = 0

    def _make_learner_group(self):
        cfg = self.algo_config
        if cfg.num_learners > 0:
            raise NotImplementedError(
                "multi-agent PPO currently runs a local learner "
                "(num_learners=0); scale sampling with num_env_runners")
        learner_cfg = {
            "lr": cfg.lr, "grad_clip": cfg.grad_clip,
            "clip_param": cfg.clip_param,
            "vf_clip_param": cfg.vf_clip_param,
            "vf_loss_coeff": cfg.vf_loss_coeff,
            "entropy_coeff": cfg.entropy_coeff,
        }
        return LearnerGroup(MultiAgentPPOLearner, self.module_spec,
                            learner_cfg, num_learners=0, seed=cfg.seed)

    def training_step(self) -> Dict[str, Any]:
        cfg = self.algo_config
        batches = self._sample(cfg.rollout_fragment_length)
        train_batch = self._postprocess_ma(batches)
        metrics = self.learner_group.update(
            train_batch, minibatch_size=cfg.minibatch_size,
            num_epochs=cfg.num_epochs)
        self._sync_runner_weights()
        self._iteration += 1
        metrics["num_env_steps_sampled"] = int(sum(
            len(b["obs"]) for b in train_batch.values()))
        return metrics

    def _postprocess_ma(self, batches: List[Dict[str, Dict[str, np.ndarray]]]
                        ) -> Dict[str, Dict[str, np.ndarray]]:
        weights = self.learner_group.get_weights()
        module = (self.local_runner.module if self.local_runner is not None
                  else MultiAgentRLModuleSpec(
                      self.module_spec["module_specs"]).build())
        out: Dict[str, List[Dict[str, np.ndarray]]] = {}
        for ma_b in batches:
            for mid, b in ma_b.items():
                last_out = module[mid].forward_train(weights[mid],
                                                     b["next_obs"])
                last_values = np.asarray(last_out["vf_preds"])
                adv, ret = compute_gae(
                    b["rewards"], b["vf_preds"], b["terminateds"],
                    b["truncateds"], last_values, self.algo_config.gamma,
                    self.algo_config.lam)
                t_len, n = b["rewards"].shape
                flat = {
                    "obs": b["obs"].reshape(t_len * n, -1),
                    "actions": b["actions"].reshape(
                        t_len * n, *b["actions"].shape[2:]),
                    "action_logp": b["action_logp"].reshape(-1),
                    "vf_preds": b["vf_preds"].reshape(-1),
                    "advantages": adv.reshape(-1),
                    "value_targets": ret.reshape(-1),
                }
                out.setdefault(mid, []).append(flat)
        merged: Dict[str, Dict[str, np.ndarray]] = {}
        for mid, parts in out.items():
            m = {k: np.concatenate([p[k] for p in parts]) for k in parts[0]}
            a = m["advantages"]
            m["advantages"] = ((a - a.mean()) / max(a.std(), 1e-6)
                               ).astype(np.float32)
            merged[mid] = m
        return merged
