"""RLModule: framework-native model abstraction for RL.

Role analog: ``rllib/core/rl_module/rl_module.py`` (the new-API-stack
replacement for ModelV2). A JaxRLModule is a pure-function bundle over a
param pytree: ``init`` builds params, ``forward_exploration`` /
``forward_inference`` / ``forward_train`` mirror the reference's three
forward modes. The default module is an MLP actor-critic (discrete or
continuous); everything jits and shards like any other param pytree.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@dataclass(frozen=True)
class RLModuleSpec:
    """Construction-from-config (reference ``SingleAgentRLModuleSpec``).

    When ``conv_filters``/``obs_shape`` are set (catalog-selected for
    image observations), the module runs a shared CNN encoder trunk with
    dense pi/vf heads; otherwise separate MLP trunks (the reference's
    default non-shared encoder layout for vector obs).
    """

    observation_dim: int
    action_dim: int
    discrete: bool = True
    hidden: Tuple[int, ...] = (64, 64)
    activation: str = "tanh"
    conv_filters: Optional[Tuple[Tuple[int, int, int], ...]] = None
    obs_shape: Optional[Tuple[int, ...]] = None

    def build(self) -> "JaxRLModule":
        return JaxRLModule(self)


def _act(name: str):
    return {"tanh": jnp.tanh, "relu": jax.nn.relu,
            "gelu": jax.nn.gelu, "silu": jax.nn.silu}[name]


def _mlp_init(key, sizes: Sequence[int], *,
              zero_last: bool = False) -> Dict[str, Any]:
    """He-init MLP params. ``zero_last`` starts the output layer at zero
    (DreamerV3 head init: reward/critic/actor heads open neutral instead
    of emitting large random values for the losses to chase)."""
    layers = []
    keys = jax.random.split(key, len(sizes) - 1)
    for j, (k, (fan_in, fan_out)) in enumerate(
            zip(keys, zip(sizes[:-1], sizes[1:]))):
        scale = 0.0 if (zero_last and j == len(sizes) - 2) \
            else np.sqrt(2.0 / fan_in)
        w = jax.random.normal(k, (fan_in, fan_out), jnp.float32) * scale
        layers.append({"w": w, "b": jnp.zeros((fan_out,), jnp.float32)})
    return {"layers": layers}


def _mlp_apply(params, x, activation):
    act = _act(activation)
    layers = params["layers"]
    for i, lyr in enumerate(layers):
        x = x @ lyr["w"] + lyr["b"]
        if i < len(layers) - 1:
            x = act(x)
    return x


class JaxRLModule:
    """Actor-critic module: pi (policy head) + vf (value head)."""

    def __init__(self, spec: RLModuleSpec):
        self.spec = spec

    def __post_init_encoder(self):
        from ray_tpu.rllib.catalog import CNNEncoderConfig

        return CNNEncoderConfig(
            obs_shape=tuple(self.spec.obs_shape),
            filters=tuple(tuple(f) for f in self.spec.conv_filters),
            activation=self.spec.activation)

    def init(self, rng: jax.Array) -> Dict[str, Any]:
        k_pi, k_vf, k_logstd = jax.random.split(rng, 3)
        out_dim = self.spec.action_dim
        if self.spec.conv_filters is not None:
            enc = self.__post_init_encoder()
            k_enc, k_pi, k_vf = jax.random.split(k_pi, 3)
            params = {
                "enc": enc.init(k_enc),
                "pi": _mlp_init(k_pi, (enc.output_dim, out_dim)),
                "vf": _mlp_init(k_vf, (enc.output_dim, 1)),
            }
        else:
            params = {
                "pi": _mlp_init(k_pi, (self.spec.observation_dim,
                                       *self.spec.hidden, out_dim)),
                "vf": _mlp_init(k_vf, (self.spec.observation_dim,
                                       *self.spec.hidden, 1)),
            }
        if not self.spec.discrete:
            params["log_std"] = jnp.zeros((out_dim,), jnp.float32)
        return params

    # -- forward modes ----------------------------------------------------

    def forward_train(self, params, obs) -> Dict[str, jax.Array]:
        if self.spec.conv_filters is not None:
            feats = self.__post_init_encoder().apply(params["enc"], obs)
            logits = _mlp_apply(params["pi"], feats, self.spec.activation)
            vf = _mlp_apply(params["vf"], feats, self.spec.activation)[..., 0]
        else:
            logits = _mlp_apply(params["pi"], obs, self.spec.activation)
            vf = _mlp_apply(params["vf"], obs, self.spec.activation)[..., 0]
        out = {"action_dist_inputs": logits, "vf_preds": vf}
        if not self.spec.discrete:
            out["log_std"] = params["log_std"]
        return out

    def forward_exploration(self, params, obs, rng) -> Dict[str, jax.Array]:
        out = self.forward_train(params, obs)
        logits = out["action_dist_inputs"]
        if self.spec.discrete:
            action = jax.random.categorical(rng, logits)
            logp = jax.nn.log_softmax(logits)[
                jnp.arange(logits.shape[0]), action]
        else:
            std = jnp.exp(out["log_std"])
            noise = jax.random.normal(rng, logits.shape)
            action = logits + std * noise
            logp = _diag_gaussian_logp(action, logits, out["log_std"])
        out["actions"] = action
        out["action_logp"] = logp
        return out

    def forward_inference(self, params, obs) -> Dict[str, jax.Array]:
        out = self.forward_train(params, obs)
        logits = out["action_dist_inputs"]
        out["actions"] = (jnp.argmax(logits, axis=-1) if self.spec.discrete
                          else logits)
        return out

    # -- distribution helpers --------------------------------------------

    def logp_entropy(self, params_out: Dict[str, jax.Array],
                     actions) -> Tuple[jax.Array, jax.Array]:
        logits = params_out["action_dist_inputs"]
        if self.spec.discrete:
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(
                logp_all, actions[..., None].astype(jnp.int32), axis=-1)[..., 0]
            p = jnp.exp(logp_all)
            entropy = -(p * logp_all).sum(-1)
        else:
            log_std = params_out["log_std"]
            logp = _diag_gaussian_logp(actions, logits, log_std)
            entropy = (0.5 * (1.0 + np.log(2 * np.pi)) + log_std).sum(-1)
            entropy = jnp.broadcast_to(entropy, logp.shape)
        return logp, entropy


def _diag_gaussian_logp(x, mean, log_std):
    var = jnp.exp(2 * log_std)
    return (-0.5 * ((x - mean) ** 2 / var + 2 * log_std +
                    np.log(2 * np.pi))).sum(-1)


def spec_for_env(env) -> RLModuleSpec:
    """Space→spec via the model catalog: image obs (3D boxes) get the
    CNN encoder stack, vector obs the MLP default."""
    from ray_tpu.rllib.catalog import Catalog

    obs_space = env.single_observation_space if hasattr(
        env, "single_observation_space") else env.observation_space
    act_space = env.single_action_space if hasattr(
        env, "single_action_space") else env.action_space
    return Catalog.from_spaces(obs_space, act_space).to_module_spec()
