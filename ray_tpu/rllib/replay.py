"""Replay buffers: uniform + prioritized.

Role analog: ``rllib/utils/replay_buffers/`` (the episode/prioritized
variants used by DQN/SAC). Numpy ring buffers; sampling returns column
batches ready for the jitted learner step.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np


class ReplayBuffer:
    """Uniform-sampling ring buffer of transition dicts."""

    def __init__(self, capacity: int = 100_000, seed: int = 0):
        self.capacity = capacity
        self._storage: Dict[str, np.ndarray] = {}
        self._idx = 0
        self._size = 0
        self._rng = np.random.default_rng(seed)

    def __len__(self):
        return self._size

    def add(self, batch: Dict[str, np.ndarray]) -> None:
        n = len(next(iter(batch.values())))
        if not self._storage:
            for k, v in batch.items():
                self._storage[k] = np.zeros((self.capacity,) + v.shape[1:],
                                            v.dtype)
        for i in range(n):
            for k, v in batch.items():
                self._storage[k][self._idx] = v[i]
            self._idx = (self._idx + 1) % self.capacity
            self._size = min(self._size + 1, self.capacity)

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        idx = self._rng.integers(0, self._size, batch_size)
        return {k: v[idx] for k, v in self._storage.items()}


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional prioritized replay (Schaul et al. 2015)."""

    def __init__(self, capacity: int = 100_000, alpha: float = 0.6,
                 beta: float = 0.4, seed: int = 0):
        super().__init__(capacity, seed)
        self.alpha = alpha
        self.beta = beta
        self._priorities = np.zeros((capacity,), np.float64)
        self._max_priority = 1.0

    def add(self, batch: Dict[str, np.ndarray]) -> None:
        n = len(next(iter(batch.values())))
        start = self._idx
        super().add(batch)
        for off in range(n):
            self._priorities[(start + off) % self.capacity] = \
                self._max_priority

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        prios = self._priorities[:self._size] ** self.alpha
        probs = prios / prios.sum()
        idx = self._rng.choice(self._size, batch_size, p=probs)
        weights = (self._size * probs[idx]) ** (-self.beta)
        weights = weights / weights.max()
        out = {k: v[idx] for k, v in self._storage.items()}
        out["weights"] = weights.astype(np.float32)
        out["batch_indexes"] = idx.astype(np.int64)
        return out

    def update_priorities(self, indexes: np.ndarray,
                          priorities: np.ndarray) -> None:
        priorities = np.abs(priorities) + 1e-6
        self._priorities[indexes] = priorities
        self._max_priority = max(self._max_priority, priorities.max())
