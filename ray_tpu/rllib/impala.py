"""IMPALA: asynchronous sampling + V-trace off-policy correction.

Role analog: ``rllib/algorithms/impala/impala.py`` (async sample fan-out,
learner decoupled from sampling; aggregation tree :676-696 is subsumed by
the object store — batches ship as refs and concat on the learner side).
V-trace follows the published recursion (Espeholt et al. 2018), computed
host-side like PPO's GAE.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.learner import JaxLearner, LearnerGroup, masked_mean


def compute_vtrace(behavior_logp, target_logp, rewards, values, dones,
                   last_values, gamma: float,
                   clip_rho: float = 1.0, clip_c: float = 1.0):
    """V-trace targets over [T, N] arrays (host-side numpy)."""
    t_len, n = rewards.shape
    rhos = np.exp(target_logp - behavior_logp)
    clipped_rho = np.minimum(rhos, clip_rho)
    cs = np.minimum(rhos, clip_c)
    nonterminal = 1.0 - dones.astype(np.float32)

    next_values = np.concatenate([values[1:], last_values[None]], axis=0)
    deltas = clipped_rho * (rewards + gamma * next_values * nonterminal
                            - values)
    vs_minus_v = np.zeros((t_len + 1, n), np.float32)
    for t in range(t_len - 1, -1, -1):
        vs_minus_v[t] = deltas[t] + gamma * cs[t] * nonterminal[t] * \
            vs_minus_v[t + 1]
    vs = vs_minus_v[:-1] + values
    next_vs = np.concatenate([vs[1:], last_values[None]], axis=0)
    pg_advantages = clipped_rho * (
        rewards + gamma * next_vs * nonterminal - values)
    return vs, pg_advantages


class ImpalaLearner(JaxLearner):
    def compute_loss(self, params, batch):
        import jax.numpy as jnp

        cfg = self.config
        vf_coeff = cfg.get("vf_loss_coeff", 0.5)
        ent_coeff = cfg.get("entropy_coeff", 0.01)

        mask = batch.get("loss_mask")
        out = self.module.forward_train(params, batch["obs"])
        logp, entropy = self.module.logp_entropy(out, batch["actions"])
        pg_loss = -masked_mean(logp * batch["pg_advantages"], mask)
        vf_loss = masked_mean(jnp.square(out["vf_preds"] - batch["vs"]), mask)
        ent = masked_mean(entropy, mask)
        loss = pg_loss + vf_coeff * vf_loss - ent_coeff * ent
        return loss, {"policy_loss": pg_loss, "vf_loss": vf_loss,
                      "entropy": ent}


class _Aggregator:
    """Aggregation-tree worker (reference ``impala.py:676-696``): pulls
    sample batches, runs the v-trace postprocess with current weights, and
    hands the learner ONE train-ready batch — ingest compute scales with
    aggregators instead of piling on the driver/learner."""

    def __init__(self, module_spec: Dict[str, Any], cfg: Dict[str, Any]):
        from ray_tpu.rllib.rl_module import RLModuleSpec

        self._module = RLModuleSpec(**{k: v for k, v in module_spec.items()
                                       if k != "kind"}).build()
        self._cfg = cfg

    def aggregate(self, weights, *batches):
        outs = [
            _vtrace_postprocess(self._module, weights, b, self._cfg)
            for b in batches
        ]
        return {k: np.concatenate([o[k] for o in outs]) for k in outs[0]}


def _vtrace_postprocess(module, weights, b, cfg: Dict[str, Any]):
    t_len, n = b["rewards"].shape
    flat_obs = b["obs"].reshape(t_len * n, -1)
    out = module.forward_train(weights, flat_obs)
    target_logp, _ = module.logp_entropy(
        out, b["actions"].reshape(t_len * n, *b["actions"].shape[2:]))
    target_logp = np.asarray(target_logp).reshape(t_len, n)
    values = np.asarray(out["vf_preds"]).reshape(t_len, n)
    last_out = module.forward_train(weights, b["next_obs"])
    last_values = np.asarray(last_out["vf_preds"])
    vs, pg_adv = compute_vtrace(
        b["action_logp"], target_logp, b["rewards"], values,
        np.logical_or(b["terminateds"], b["truncateds"]),
        last_values, cfg.get("gamma", 0.99),
        cfg.get("clip_rho", 1.0), cfg.get("clip_c", 1.0))
    # drop autoreset reset-step rows (valid=False): not real transitions;
    # the v-trace chain is already cut at the episode end one step earlier
    # so only the row itself is garbage.
    mask = b.get("valid", np.ones((t_len, n), bool)).reshape(-1)
    return {
        "obs": flat_obs[mask],
        "actions": b["actions"].reshape(
            t_len * n, *b["actions"].shape[2:])[mask],
        "pg_advantages": pg_adv.reshape(-1).astype(np.float32)[mask],
        "vs": vs.reshape(-1).astype(np.float32)[mask],
        # behavior-policy logp rides along for APPO's clipped surrogate
        # (IMPALA's plain pg loss ignores it)
        "action_logp": b["action_logp"].reshape(-1).astype(
            np.float32)[mask],
    }


class IMPALAConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or IMPALA)
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.01
        self.clip_rho = 1.0
        self.clip_c = 1.0
        self.lr = 5e-4
        self.num_epochs = 1          # off-policy: single pass
        # whole-batch update (one optimizer step per training_step): the
        # unclipped pg loss is not safe to re-step on stale data; APPO
        # overrides with real minibatching
        self.minibatch_size = None
        self.num_aggregation_workers = 0  # reference impala.py:676-696

    def copy(self):
        import copy as _copy

        return _copy.deepcopy(self)


class IMPALA(Algorithm):
    config_cls = IMPALAConfig

    def _make_learner_group(self):
        cfg = self.algo_config
        learner_cfg = {
            "lr": cfg.lr, "grad_clip": cfg.grad_clip,
            "vf_loss_coeff": getattr(cfg, "vf_loss_coeff", 0.5),
            "entropy_coeff": getattr(cfg, "entropy_coeff", 0.01),
        }
        return LearnerGroup(ImpalaLearner, self.module_spec, learner_cfg,
                            num_learners=cfg.num_learners, seed=cfg.seed)

    def _setup_algo(self):
        super()._setup_algo()
        self._inflight: Dict[Any, int] = {}
        self._aggregators: List[Any] = []
        self._agg_rr = 0
        n_agg = getattr(self.algo_config, "num_aggregation_workers", 0)
        if n_agg > 0:
            import ray_tpu

            cfg = self.algo_config
            agg_cfg = {"gamma": cfg.gamma,
                       "clip_rho": getattr(cfg, "clip_rho", 1.0),
                       "clip_c": getattr(cfg, "clip_c", 1.0)}
            cls = ray_tpu.remote(_Aggregator)
            self._aggregators = [
                cls.options(num_cpus=1).remote(self.module_spec, agg_cfg)
                for _ in range(n_agg)]

    def cleanup(self) -> None:
        super().cleanup()
        import ray_tpu

        for agg in self._aggregators:
            try:
                ray_tpu.kill(agg)
            except Exception:
                pass
        self._aggregators = []

    def training_step(self) -> Dict[str, Any]:
        """Async: keep one sample() in flight per runner; update on what
        arrives this tick (the learner never waits for stragglers)."""
        import ray_tpu

        cfg = self.algo_config
        if self.env_runner_group is None:
            batches = [self.local_runner.sample(cfg.rollout_fragment_length)]
        else:
            # launch/refresh in-flight sampling on every healthy runner
            for i in self.env_runner_group.healthy_ids():
                actor = self.env_runner_group._actors[i]
                if i not in self._inflight:
                    self._inflight[i] = actor.sample.remote(
                        cfg.rollout_fragment_length)
            ready, _ = ray_tpu.wait(list(self._inflight.values()),
                                    num_returns=1, timeout=60)
            batches = []
            done_ids = [i for i, r in self._inflight.items() if r in ready]
            for i in done_ids:
                try:
                    batches.append(ray_tpu.get(self._inflight.pop(i)))
                except Exception:
                    self.env_runner_group._healthy[i] = False
            self.env_runner_group.probe_and_restore()
            if not batches:
                return {"num_env_steps_sampled": 0}

        train_batch = self._postprocess(batches)
        # IMPALA defaults to a single pass; APPO's clipped surrogate makes
        # multi-epoch minibatch reuse safe (its config raises num_epochs)
        metrics = self.learner_group.update(
            train_batch,
            minibatch_size=getattr(cfg, "minibatch_size", None),
            num_epochs=getattr(cfg, "num_epochs", 1))
        self._sync_runner_weights()
        self._iteration += 1
        metrics["num_env_steps_sampled"] = len(train_batch["obs"])
        return metrics

    def _postprocess(self, batches: List[Dict[str, np.ndarray]]
                     ) -> Dict[str, np.ndarray]:
        cfg = self.algo_config
        weights = self.learner_group.get_weights()
        if self._aggregators:
            # aggregation tree: fan batches over aggregator actors,
            # round-robin; weights ship once as a shared ref
            import ray_tpu

            w_ref = ray_tpu.put(weights)
            refs = []
            n_agg = len(self._aggregators)
            for i in range(n_agg):
                mine = batches[i::n_agg]
                if not mine:
                    continue
                agg = self._aggregators[(self._agg_rr + i) % n_agg]
                refs.append(agg.aggregate.remote(w_ref, *mine))
            self._agg_rr += 1
            try:
                outs = ray_tpu.get(refs)
            finally:
                # a weights blob per step would accumulate forever (no
                # distributed refcounting): free it even when an
                # aggregator died mid-step
                ray_tpu.free(w_ref)
            return {k: np.concatenate([o[k] for o in outs])
                    for k in outs[0]}
        from ray_tpu.rllib.rl_module import RLModuleSpec

        module = RLModuleSpec(**self.module_spec).build()
        agg_cfg = {"gamma": cfg.gamma,
                   "clip_rho": getattr(cfg, "clip_rho", 1.0),
                   "clip_c": getattr(cfg, "clip_c", 1.0)}
        outs = [_vtrace_postprocess(module, weights, b, agg_cfg)
                for b in batches]
        return {k: np.concatenate([o[k] for o in outs]) for k in outs[0]}
