"""Anakin: fully-jitted on-device PPO (rollout + learn in one XLA program).

TPU-native RL beyond the reference's capabilities: the reference's fastest
path still ships sample batches host→learner (SURVEY §3.5); the podracer
"Anakin" architecture (PAPERS.md, Hessel et al. 2021 — pattern only) keeps
envs, policy, GAE, and SGD in a single jitted step over vmapped pure-JAX
envs, so the MXU never waits on hosts. Scales over the mesh's dp axis by
sharding the env batch; gradient sync is the psum XLA inserts.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.jax_env import make_jax_env
from ray_tpu.rllib.rl_module import RLModuleSpec


class AnakinState(NamedTuple):
    params: Any
    opt_state: Any
    env_states: Any          # vmapped EnvState
    key: jax.Array
    # running episode stats (per env): current return/len + last completed
    ep_return: jax.Array
    ep_len: jax.Array
    last_return: jax.Array


class AnakinPPO:
    """Config-light fully-jitted PPO."""

    def __init__(self, env_name: str = "CartPole-v1", *,
                 num_envs: int = 64, rollout_len: int = 32,
                 lr: float = 3e-4, gamma: float = 0.99, lam: float = 0.95,
                 clip: float = 0.2, vf_coeff: float = 0.5,
                 entropy_coeff: float = 0.01, num_epochs: int = 4,
                 num_minibatches: int = 4, seed: int = 0,
                 hidden: Tuple[int, ...] = (64, 64)):
        self.env = make_jax_env(env_name)
        self.spec = RLModuleSpec(
            observation_dim=self.env.observation_dim,
            action_dim=self.env.action_dim, discrete=True, hidden=hidden)
        self.module = self.spec.build()
        self.cfg = dict(num_envs=num_envs, rollout_len=rollout_len,
                        gamma=gamma, lam=lam, clip=clip, vf_coeff=vf_coeff,
                        entropy_coeff=entropy_coeff, num_epochs=num_epochs,
                        num_minibatches=num_minibatches)
        self.optimizer = optax.chain(optax.clip_by_global_norm(0.5),
                                     optax.adam(lr))

        key = jax.random.PRNGKey(seed)
        key, pkey, ekey = jax.random.split(key, 3)
        params = self.module.init(pkey)
        env_states = jax.vmap(self.env.reset)(
            jax.random.split(ekey, num_envs))
        self.state = AnakinState(
            params=params,
            opt_state=self.optimizer.init(params),
            env_states=env_states,
            key=key,
            ep_return=jnp.zeros((num_envs,)),
            ep_len=jnp.zeros((num_envs,), jnp.int32),
            last_return=jnp.zeros((num_envs,)),
        )
        from ray_tpu.util.device_plane import registered_jit

        self._step_fn = registered_jit(self._train_iteration,
                                       name="rllib::anakin_iteration",
                                       component="rllib",
                                       donate_argnums=(0,))

    # -- the single fused program ----------------------------------------

    def _rollout(self, state: AnakinState):
        cfg = self.cfg

        def step(carry, _):
            env_states, key, ep_ret, ep_len, last_ret = carry
            obs = env_states.obs                      # [N, D]
            key, akey = jax.random.split(key)
            out = self.module.forward_exploration(state.params, obs, akey)
            step_out = jax.vmap(self.env.step)(env_states, out["actions"])
            ep_ret = ep_ret + step_out.reward
            ep_len = ep_len + 1
            last_ret = jnp.where(step_out.done, ep_ret, last_ret)
            ep_ret = jnp.where(step_out.done, 0.0, ep_ret)
            ep_len = jnp.where(step_out.done, 0, ep_len)
            traj = {
                "obs": obs,
                "actions": out["actions"],
                "logp": out["action_logp"],
                "value": out["vf_preds"],
                "reward": step_out.reward,
                "done": step_out.done,
            }
            return (step_out.state, key, ep_ret, ep_len, last_ret), traj

        (env_states, key, ep_ret, ep_len, last_ret), traj = jax.lax.scan(
            step,
            (state.env_states, state.key, state.ep_return, state.ep_len,
             state.last_return),
            None, length=cfg["rollout_len"])
        return env_states, key, ep_ret, ep_len, last_ret, traj

    def _gae(self, traj, last_value):
        cfg = self.cfg
        nonterminal = 1.0 - traj["done"].astype(jnp.float32)

        def back(carry, inp):
            gae = carry
            reward, value, nextv, nonterm = inp
            delta = reward + cfg["gamma"] * nextv * nonterm - value
            gae = delta + cfg["gamma"] * cfg["lam"] * nonterm * gae
            return gae, gae

        next_values = jnp.concatenate(
            [traj["value"][1:], last_value[None]], axis=0)
        _, adv = jax.lax.scan(
            back, jnp.zeros_like(last_value),
            (traj["reward"], traj["value"], next_values, nonterminal),
            reverse=True)
        returns = adv + traj["value"]
        return adv, returns

    def _loss(self, params, batch):
        cfg = self.cfg
        out = self.module.forward_train(params, batch["obs"])
        logp, entropy = self.module.logp_entropy(out, batch["actions"])
        ratio = jnp.exp(logp - batch["logp"])
        adv = batch["adv"]
        surr = jnp.minimum(ratio * adv,
                           jnp.clip(ratio, 1 - cfg["clip"],
                                    1 + cfg["clip"]) * adv)
        vf_loss = jnp.mean((out["vf_preds"] - batch["returns"]) ** 2)
        loss = (-surr.mean() + cfg["vf_coeff"] * vf_loss
                - cfg["entropy_coeff"] * entropy.mean())
        return loss, {"policy_loss": -surr.mean(), "vf_loss": vf_loss,
                      "entropy": entropy.mean()}

    def _train_iteration(self, state: AnakinState):
        cfg = self.cfg
        env_states, key, ep_ret, ep_len, last_ret, traj = self._rollout(state)

        last_out = self.module.forward_train(state.params,
                                             env_states.obs)
        adv, returns = self._gae(traj, last_out["vf_preds"])
        t_len, n = traj["reward"].shape
        flat = {
            "obs": traj["obs"].reshape(t_len * n, -1),
            "actions": traj["actions"].reshape(-1),
            "logp": traj["logp"].reshape(-1),
            "adv": ((adv - adv.mean()) /
                    (adv.std() + 1e-6)).reshape(-1),
            "returns": returns.reshape(-1),
        }

        def epoch(carry, ekey):
            params, opt_state = carry
            perm = jax.random.permutation(ekey, t_len * n)
            mb_size = (t_len * n) // cfg["num_minibatches"]

            def minibatch(carry, i):
                params, opt_state = carry
                idx = jax.lax.dynamic_slice_in_dim(
                    perm, i * mb_size, mb_size)
                mb = {k: v[idx] for k, v in flat.items()}
                (_, metrics), grads = jax.value_and_grad(
                    self._loss, has_aux=True)(params, mb)
                updates, opt_state = self.optimizer.update(
                    grads, opt_state, params)
                params = optax.apply_updates(params, updates)
                return (params, opt_state), metrics

            (params, opt_state), metrics = jax.lax.scan(
                minibatch, (params, opt_state),
                jnp.arange(cfg["num_minibatches"]))
            return (params, opt_state), metrics

        key, *ekeys = jax.random.split(key, cfg["num_epochs"] + 1)
        (params, opt_state), metrics = jax.lax.scan(
            epoch, (state.params, state.opt_state), jnp.stack(ekeys))

        new_state = AnakinState(
            params=params, opt_state=opt_state, env_states=env_states,
            key=key, ep_return=ep_ret, ep_len=ep_len, last_return=last_ret)
        out_metrics = {k: v.mean() for k, v in metrics.items()}
        out_metrics["episode_return_mean"] = last_ret.mean()
        return new_state, out_metrics

    # -- public API -------------------------------------------------------

    def train(self) -> Dict[str, float]:
        self.state, metrics = self._step_fn(self.state)
        return {k: float(jax.device_get(v)) for k, v in metrics.items()}

    @property
    def params(self):
        return self.state.params
