"""DreamerV3: model-based RL — RSSM world model + imagination actor-critic.

Reference role: ``rllib/algorithms/dreamerv3/dreamerv3.py`` (the reference
implementation is TensorFlow-only; this is a from-scratch JAX design, which
is exactly the TPU-first point: the three training phases — world-model
fit, imagination rollout, actor/critic update — are each a ``lax.scan``
inside ONE jitted update, so a full DreamerV3 step is a single device
program with no host round-trips).

Compact-but-faithful choices (Hafner et al. 2023, arXiv:2301.04104):

- RSSM with deterministic GRU state ``h`` and categorical stochastic
  state ``z`` (``groups x classes`` one-hots, straight-through gradients,
  1% uniform mix on the logits);
- symlog squared-error reconstruction and reward heads, Bernoulli
  continue head;
- KL balance: ``L_dyn = KL(sg(post) || prior)``, ``L_rep = KL(post ||
  sg(prior))`` with free bits (clip at 1 nat) and weights 0.5 / 0.1;
- imagination from every posterior state for ``horizon`` steps with the
  frozen world model; lambda-returns (lambda 0.95) against a slow EMA
  critic; actor trained with REINFORCE on return-range-normalized
  advantages (the 5th-95th percentile scale EMA) + entropy bonus.

- twohot discrete regression for the critic (41 bins over symlog value
  space, value = softmax expectation over symexp'd bin centers): the
  paper's stabilizer — a symlog-MSE critic bootstrapping its own
  symexp'd output diverges (measured: imagined return 3.7 -> 320 over
  400 updates on the dev toy env before this was added).

Omission vs the paper (disclosed): image encoder/decoder — vector obs
only; the catalog's CNN trunk could slot into ``_enc``/``_dec``.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np


def symlog(x):
    import jax.numpy as jnp

    return jnp.sign(x) * jnp.log1p(jnp.abs(x))


def symexp(x):
    import jax.numpy as jnp

    return jnp.sign(x) * (jnp.exp(jnp.abs(x)) - 1.0)


class DreamerV3Learner:
    """World model + actor + critic, three Adam optimizers, one jitted
    ``update(batch)`` over sequence batches.

    ``batch``: dict of [B, T, ...] arrays — ``obs`` [B,T,D] float,
    ``actions`` [B,T] int32, ``rewards`` [B,T], ``continues`` [B,T]
    (1.0 until terminal). Returns metrics (world-model losses, imagined
    return, actor entropy).
    """

    def __init__(self, module_spec_dict: Dict[str, Any],
                 config: Optional[Dict[str, Any]] = None, seed: int = 0):
        import jax
        import optax

        cfg = dict(config or {})
        self.config = cfg
        self.obs_dim = int(module_spec_dict["observation_dim"])
        self.n_actions = int(module_spec_dict["action_dim"])
        if not module_spec_dict.get("discrete", True):
            raise ValueError("DreamerV3Learner: discrete actions only "
                             "(continuous actor is a straightforward "
                             "extension; not needed by the test envs)")
        self.deter = int(cfg.get("deter", 128))
        self.groups = int(cfg.get("groups", 8))
        self.classes = int(cfg.get("classes", 8))
        self.hidden = int(cfg.get("hidden", 128))
        self.horizon = int(cfg.get("horizon", 10))
        self.gamma = float(cfg.get("gamma", 0.985))
        self.lam = float(cfg.get("lambda", 0.95))
        self.entropy_coef = float(cfg.get("entropy_coef", 3e-4))
        self.unimix = float(cfg.get("unimix", 0.01))
        self.free_bits = float(cfg.get("free_bits", 1.0))
        self.critic_ema = float(cfg.get("critic_ema", 0.98))

        self.zdim = self.groups * self.classes
        # twohot critic bins: uniform in symlog space, so the softmax
        # expectation spans large magnitudes with fine resolution near 0
        self.n_bins = int(cfg.get("critic_bins", 41))
        self._bin_lim = float(cfg.get("critic_bin_limit", 10.0))
        key = jax.random.PRNGKey(seed)
        self.params = self._init_params(key)
        self.opt = {
            "wm": optax.chain(optax.clip_by_global_norm(1000.0),
                              optax.adam(cfg.get("wm_lr", 1e-3))),
            "actor": optax.chain(optax.clip_by_global_norm(100.0),
                                 optax.adam(cfg.get("actor_lr", 3e-4))),
            "critic": optax.chain(optax.clip_by_global_norm(100.0),
                                  optax.adam(cfg.get("critic_lr", 3e-4))),
        }
        self.opt_state = {k: self.opt[k].init(self.params[k])
                          for k in self.opt}
        # slow critic (return targets) + return-scale EMA state
        self.slow_critic = jax.tree.map(lambda a: a, self.params["critic"])
        self.retnorm = np.array([0.0, 1.0], np.float32)  # [lo, hi] EMA
        from ray_tpu.util.device_plane import registered_jit

        self._update_fn = registered_jit(self._update,
                                         name="rllib::dreamer_update",
                                         component="rllib")
        self._rng = jax.random.PRNGKey(seed + 1)

    # -- params -----------------------------------------------------------

    def _mlp(self, key, sizes, zero_last: bool = False):
        # shared helper (rl_module.py); zero_last = the paper's head
        # init — the twohot critic opens at exactly value 0 instead of
        # +-thousands of symexp bin noise for the actor to chase
        from ray_tpu.rllib.rl_module import _mlp_init

        return _mlp_init(key, sizes, zero_last=zero_last)

    def _init_params(self, key):
        import jax
        import jax.numpy as jnp

        ks = jax.random.split(key, 10)
        d, z, h, a = self.deter, self.zdim, self.hidden, self.n_actions
        wm = {
            "enc": self._mlp(ks[0], (self.obs_dim, h, h)),
            # GRU: input [z + a_onehot], 3 gates
            "gru_x": self._mlp(ks[1], (z + a, 3 * d)),
            "gru_h": {"w": jax.random.normal(ks[2], (d, 3 * d), jnp.float32)
                      * np.sqrt(1.0 / d)},
            "prior": self._mlp(ks[3], (d, h, z)),
            "post": self._mlp(ks[4], (d + h, h, z)),
            "dec": self._mlp(ks[5], (d + z, h, self.obs_dim)),
            "reward": self._mlp(ks[6], (d + z, h, 1), zero_last=True),
            "cont": self._mlp(ks[7], (d + z, h, 1), zero_last=True),
        }
        actor = self._mlp(ks[8], (d + z, h, a), zero_last=True)
        critic = self._mlp(ks[9], (d + z, h, self.n_bins),
                           zero_last=True)
        return {"wm": wm, "actor": actor, "critic": critic}

    @staticmethod
    def _apply(p, x):
        from ray_tpu.rllib.rl_module import _mlp_apply

        return _mlp_apply(p, x, "tanh")

    # -- RSSM pieces ------------------------------------------------------

    def _gru(self, wm, hstate, x):
        import jax
        import jax.numpy as jnp

        xr, xu, xc = jnp.split(self._apply(wm["gru_x"], x), 3, axis=-1)
        hr, hu, hc = jnp.split(hstate @ wm["gru_h"]["w"], 3, axis=-1)
        r = jax.nn.sigmoid(xr + hr)
        u = jax.nn.sigmoid(xu + hu)
        cand = jnp.tanh(xc + r * hc)
        return u * hstate + (1 - u) * cand

    def _logits(self, head_params, x):
        """Head logits with the 1% uniform mix (keeps KL finite and
        exploration alive), shaped [..., groups, classes]."""
        import jax
        import jax.numpy as jnp

        logits = self._apply(head_params, x)
        logits = logits.reshape(*logits.shape[:-1], self.groups,
                                self.classes)
        probs = jax.nn.softmax(logits, -1)
        probs = (1 - self.unimix) * probs + self.unimix / self.classes
        return jnp.log(probs)

    def _sample_st(self, rng, logits):
        """Straight-through categorical sample -> flat one-hot [..., z]."""
        import jax
        import jax.numpy as jnp

        idx = jax.random.categorical(rng, logits, axis=-1)
        onehot = jax.nn.one_hot(idx, self.classes, dtype=logits.dtype)
        probs = jax.nn.softmax(logits, -1)
        st = onehot + probs - jax.lax.stop_gradient(probs)
        return st.reshape(*st.shape[:-2], self.zdim)

    # -- twohot value head -------------------------------------------------

    def _value(self, critic_params, feats):
        """Critic value: softmax expectation over symexp'd bin centers."""
        import jax
        import jax.numpy as jnp

        logits = self._apply(critic_params, feats)
        centers = symexp(jnp.linspace(-self._bin_lim, self._bin_lim,
                                      self.n_bins))
        return jax.nn.softmax(logits, -1) @ centers

    def _twohot(self, x):
        """Twohot encoding of symlog(x) over the uniform symlog bins:
        probability mass split between the two nearest bin centers so the
        encoding's expectation reproduces x exactly (within the bin
        range)."""
        import jax
        import jax.numpy as jnp

        s = jnp.clip(symlog(x), -self._bin_lim, self._bin_lim)
        pos = (s + self._bin_lim) / (2 * self._bin_lim) * (self.n_bins - 1)
        lo = jnp.clip(jnp.floor(pos).astype(jnp.int32), 0,
                      self.n_bins - 2)
        w = pos - lo
        return (jax.nn.one_hot(lo, self.n_bins) * (1 - w)[..., None]
                + jax.nn.one_hot(lo + 1, self.n_bins) * w[..., None])

    @staticmethod
    def _kl(lhs_logits, rhs_logits):
        """KL(lhs || rhs) summed over groups (both log-prob tensors)."""
        import jax
        import jax.numpy as jnp

        p = jax.nn.softmax(lhs_logits, -1)
        return (p * (lhs_logits - rhs_logits)).sum(-1).sum(-1)

    # -- world-model loss over a sequence batch ---------------------------

    def _wm_observe(self, wm, obs, actions, rng):
        """Scan the RSSM over time: returns (h, z) features per step and
        prior/post logits. obs [B,T,D], actions [B,T] (action TAKEN at
        each step, conditioning the NEXT state)."""
        import jax
        import jax.numpy as jnp

        B, T = obs.shape[:2]
        embed = self._apply(wm["enc"], symlog(obs))  # [B,T,h]
        a_onehot = jax.nn.one_hot(actions, self.n_actions)
        rngs = jax.random.split(rng, T)

        def step(carry, xs):
            hstate, z = carry
            emb_t, a_prev, r = xs
            hstate = self._gru(wm, hstate, jnp.concatenate(
                [z, a_prev], -1))
            prior_logits = self._logits(wm["prior"], hstate)
            post_logits = self._logits(wm["post"], jnp.concatenate(
                [hstate, emb_t], -1))
            z = self._sample_st(r, post_logits)
            return (hstate, z), (hstate, z, prior_logits, post_logits)

        h0 = jnp.zeros((B, self.deter))
        z0 = jnp.zeros((B, self.zdim))
        # a_prev at t is the action taken at t-1 (zero-pad the first)
        a_prev = jnp.concatenate(
            [jnp.zeros_like(a_onehot[:, :1]), a_onehot[:, :-1]], 1)
        (_, _), (hs, zs, priors, posts) = jax.lax.scan(
            step, (h0, z0),
            (embed.swapaxes(0, 1), a_prev.swapaxes(0, 1), rngs))
        # time-major -> batch-major
        sw = lambda x: x.swapaxes(0, 1)
        return sw(hs), sw(zs), sw(priors), sw(posts)

    def _wm_loss(self, wm, batch, rng):
        import jax
        import jax.numpy as jnp

        obs, actions = batch["obs"], batch["actions"]
        hs, zs, priors, posts = self._wm_observe(wm, obs, actions, rng)
        feat = jnp.concatenate([hs, zs], -1)
        obs_hat = self._apply(wm["dec"], feat)
        rew_hat = self._apply(wm["reward"], feat)[..., 0]
        cont_logit = self._apply(wm["cont"], feat)[..., 0]

        import optax

        recon = ((obs_hat - symlog(obs)) ** 2).sum(-1)
        rew = (rew_hat - symlog(batch["rewards"])) ** 2
        cont = optax.sigmoid_binary_cross_entropy(cont_logit,
                                                  batch["continues"])
        dyn = jnp.maximum(self.free_bits, self._kl(
            jax.lax.stop_gradient(posts), priors))
        rep = jnp.maximum(self.free_bits, self._kl(
            posts, jax.lax.stop_gradient(priors)))
        loss = (recon + rew + cont + 0.5 * dyn + 0.1 * rep).mean()
        metrics = {"wm_recon": recon.mean(), "wm_reward": rew.mean(),
                   "wm_cont": cont.mean(), "wm_dyn": dyn.mean(),
                   "wm_loss": loss}
        return loss, (metrics, hs, zs)

    # -- imagination + actor-critic ---------------------------------------

    def _imagine(self, wm, actor, h0, z0, rng):
        """Roll the frozen world model forward ``horizon`` steps sampling
        actions from the actor. h0/z0: [N, ...] start states (posterior
        states, flattened over B*T). Returns feats [H+1, N, ...],
        actions, logps, entropies, rewards, continues."""
        import jax
        import jax.numpy as jnp

        def step(carry, r):
            hstate, z = carry
            feat = jnp.concatenate([hstate, z], -1)
            logits = jax.nn.log_softmax(self._apply(actor, feat))
            ra, rz = jax.random.split(r)
            a = jax.random.categorical(ra, logits)
            logp = jnp.take_along_axis(logits, a[:, None], 1)[:, 0]
            ent = -(jnp.exp(logits) * logits).sum(-1)
            hstate = self._gru(wm, hstate, jnp.concatenate(
                [z, jax.nn.one_hot(a, self.n_actions)], -1))
            z = self._sample_st(rz, self._logits(wm["prior"], hstate))
            return (hstate, z), (feat, a, logp, ent)

        rngs = jax.random.split(rng, self.horizon)
        (hH, zH), (feats, acts, logps, ents) = jax.lax.scan(
            step, (h0, z0), rngs)
        featH = jnp.concatenate([hH, zH], -1)
        feats = jnp.concatenate([feats, featH[None]], 0)  # [H+1, N, F]
        rew = symexp(self._apply(wm["reward"], feats)[..., 0])
        cont = jax.nn.sigmoid(self._apply(wm["cont"], feats)[..., 0])
        return feats, acts, logps, ents, rew, cont

    def _lambda_returns(self, rewards, conts, values):
        """TD(lambda) over the imagined horizon. All [H+1, N]; returns
        [H, N] targets for steps 0..H-1."""
        import jax
        import jax.numpy as jnp

        disc = self.gamma * conts
        H = self.horizon

        def step(nxt, t):
            r = rewards[t + 1] + disc[t + 1] * (
                (1 - self.lam) * values[t + 1] + self.lam * nxt)
            return r, r

        _, rets = jax.lax.scan(step, values[H], jnp.arange(H - 1, -1, -1))
        return rets[::-1]

    # -- the one-program update -------------------------------------------

    def _update(self, params, opt_state, slow_critic, retnorm, batch, rng):
        import jax
        import jax.numpy as jnp
        import optax

        r_wm, r_im = jax.random.split(rng)

        # 1. world model
        (wm_loss, (metrics, hs, zs)), wm_grads = jax.value_and_grad(
            self._wm_loss, has_aux=True)(params["wm"], batch, r_wm)
        upd, wm_opt = self.opt["wm"].update(
            wm_grads, opt_state["wm"], params["wm"])
        wm_new = optax.apply_updates(params["wm"], upd)

        # 2. imagination from every (updated-)posterior state
        wm_f = jax.lax.stop_gradient(wm_new)
        h0 = jax.lax.stop_gradient(hs).reshape(-1, self.deter)
        z0 = jax.lax.stop_gradient(zs).reshape(-1, self.zdim)

        def actor_loss(actor_params):
            feats, acts, logps, ents, rew, cont = self._imagine(
                wm_f, actor_params, h0, z0, r_im)
            values = self._value(
                jax.lax.stop_gradient(params["critic"]), feats)
            rets = self._lambda_returns(rew, cont, values)
            # return-range normalization (5th-95th percentile EMA)
            lo = jnp.percentile(rets, 5.0)
            hi = jnp.percentile(rets, 95.0)
            new_lo = self.critic_ema * retnorm[0] + (
                1 - self.critic_ema) * lo
            new_hi = self.critic_ema * retnorm[1] + (
                1 - self.critic_ema) * hi
            scale = jnp.maximum(1.0, new_hi - new_lo)
            adv = (rets - values[:-1]) / scale
            # discount-weight imagined step t by prod of continue probs
            # AFTER the start state: weight_0 = 1, weight_t = c_1..c_t
            weight = jnp.cumprod(
                jnp.concatenate([jnp.ones_like(cont[:1]), cont[1:-1]], 0),
                0)
            pg = -(jax.lax.stop_gradient(adv * weight) * logps).mean()
            ent = ents.mean()
            loss = pg - self.entropy_coef * ent
            aux = {"rets": rets, "feats": feats, "weight": weight,
                   "imag_return": rets[0].mean(), "actor_entropy": ent,
                   "retnorm": jnp.stack([new_lo, new_hi])}
            return loss, aux

        (a_loss, aux), a_grads = jax.value_and_grad(
            actor_loss, has_aux=True)(params["actor"])
        upd, a_opt = self.opt["actor"].update(
            a_grads, opt_state["actor"], params["actor"])
        actor_new = optax.apply_updates(params["actor"], upd)

        # 3. critic on the imagined returns (+ slow-critic regularizer)
        feats = jax.lax.stop_gradient(aux["feats"][:-1])
        rets = jax.lax.stop_gradient(aux["rets"])
        weight = jax.lax.stop_gradient(aux["weight"])

        def critic_loss(cp):
            logits = jax.nn.log_softmax(self._apply(cp, feats), -1)
            tgt = self._twohot(rets)
            slow_tgt = jax.nn.softmax(
                self._apply(slow_critic, feats), -1)
            ce = -(tgt * logits).sum(-1)
            reg = -(jax.lax.stop_gradient(slow_tgt) * logits).sum(-1)
            return (weight * (ce + 0.1 * reg)).mean()

        c_loss, c_grads = jax.value_and_grad(critic_loss)(params["critic"])
        upd, c_opt = self.opt["critic"].update(
            c_grads, opt_state["critic"], params["critic"])
        critic_new = optax.apply_updates(params["critic"], upd)
        slow_new = jax.tree.map(
            lambda s, c: self.critic_ema * s + (1 - self.critic_ema) * c,
            slow_critic, critic_new)

        params = {"wm": wm_new, "actor": actor_new, "critic": critic_new}
        opt_state = {"wm": wm_opt, "actor": a_opt, "critic": c_opt}
        metrics = dict(metrics)
        metrics.update(actor_loss=a_loss, critic_loss=c_loss,
                       imag_return=aux["imag_return"],
                       actor_entropy=aux["actor_entropy"])
        return params, opt_state, slow_new, aux["retnorm"], metrics

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        import jax

        self._rng, r = jax.random.split(self._rng)
        batch = {k: jax.numpy.asarray(v) for k, v in batch.items()}
        (self.params, self.opt_state, self.slow_critic, retnorm,
         metrics) = self._update_fn(self.params, self.opt_state,
                                    self.slow_critic,
                                    jax.numpy.asarray(self.retnorm),
                                    batch, r)
        self.retnorm = np.asarray(retnorm)
        return {k: float(v) for k, v in jax.device_get(metrics).items()}

    # -- acting -----------------------------------------------------------

    def policy_state(self, batch_size: int = 1):
        """Fresh recurrent state for acting: (h, z, key)."""
        import jax

        self._rng, k = jax.random.split(self._rng)
        return (np.zeros((batch_size, self.deter), np.float32),
                np.zeros((batch_size, self.zdim), np.float32), k)

    def _act_jit(self, params, hstate, z, obs, prev_action, key, greedy):
        import jax
        import jax.numpy as jnp

        wm = params["wm"]
        a_prev = jax.nn.one_hot(prev_action, self.n_actions)
        kz, ka, knext = jax.random.split(key, 3)
        hstate = self._gru(wm, hstate, jnp.concatenate([z, a_prev], -1))
        embed = self._apply(wm["enc"], symlog(obs))
        post = self._logits(wm["post"], jnp.concatenate(
            [hstate, embed], -1))
        z = self._sample_st(kz, post)
        logits = self._apply(params["actor"],
                             jnp.concatenate([hstate, z], -1))
        a = jnp.argmax(logits, -1) if greedy \
            else jax.random.categorical(ka, logits)
        return hstate, z, a, knext

    def act(self, state, obs, prev_action, rng_seed: Optional[int] = None,
            greedy: bool = False):
        """One acting step: posterior update with the real obs, then the
        actor head — a single jitted program per call (the per-env-step
        hot path; eager dispatch would pay ~20 op round-trips on the
        tunneled backend). The PRNG key rides in the policy state and is
        split fresh each step; ``rng_seed`` optionally pins it (tests).
        Returns (new_state, action [B])."""
        import jax

        if not hasattr(self, "_act_fn"):
            from ray_tpu.util.device_plane import registered_jit

            self._act_fn = registered_jit(self._act_jit,
                                          name="rllib::dreamer_act",
                                          component="rllib",
                                          static_argnames=("greedy",))
        hstate, z, key = state
        if rng_seed is not None:
            key = jax.random.PRNGKey(rng_seed)
        hstate, z, a, knext = self._act_fn(
            self.params, jax.numpy.asarray(hstate),
            jax.numpy.asarray(z),
            jax.numpy.asarray(obs, jax.numpy.float32),
            jax.numpy.asarray(prev_action), key, greedy=greedy)
        return ((np.asarray(hstate), np.asarray(z), knext), np.asarray(a))


def train_dreamerv3(dataset_path: str, module_spec: Dict[str, Any],
                    *, config: Optional[Dict[str, Any]] = None,
                    seq_len: int = 16, batch_size: int = 16,
                    num_updates: int = 100,
                    seed: int = 0) -> DreamerV3Learner:
    """Offline DreamerV3 on recorded shards (the train_bc/train_cql
    companion): world model + imagination actor-critic from a
    single-env recording (``record_episodes(..., num_envs=1)`` — see
    ``OfflineReader.iter_sequences``)."""
    from ray_tpu.rllib.offline import OfflineReader

    reader = OfflineReader(dataset_path)
    learner = DreamerV3Learner(module_spec, config, seed=seed)
    done = 0
    metrics: Dict[str, float] = {}
    while done < num_updates:
        for batch in reader.iter_sequences(seq_len, batch_size,
                                           seed=seed + done):
            metrics = learner.update(batch)
            done += 1
            if done >= num_updates:
                break
    learner.last_metrics = metrics
    return learner
