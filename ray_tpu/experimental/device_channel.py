"""Device-tensor channels: the compiled-DAG accelerator data plane.

Role analog: the reference's NCCL channels for DAG edges
(``python/ray/experimental/channel/torch_tensor_nccl_channel.py:29``,
``nccl_group.py:18``) typed by ``TorchTensorType``
(``torch_tensor_type.py``). TPU-native shape of the idea:

- an edge annotated :class:`DeviceTensorType` carries ONE jax array whose
  payload bytes move through the channel's ring slot RAW (dtype/shape in
  a tiny header) instead of the generic pickle path;
- the reader materializes a ``jax.Array`` straight from the mapped slot:
  zero-copy via dlpack on host-mapped backends (CPU — the consumer array
  aliases the slot memory, no copy at all), one H2D DMA on TPU
  (``jax.device_put``; cross-process device memory can't be shared through
  host shm, so one hop is the floor — the reference pays the same in NCCL
  as a D2D hop);
- non-tensor control values (teardown/error sentinels) fall back to the
  pickle path transparently.

Zero-copy safety under pipelining (r13 ring rewrite): the ring's
backpressure means a slot is only overwritten ``nslots`` values later,
and the compiled DAG sizes every channel ``max_in_flight + 1`` slots —
so a stage that consumes its input before the pipeline admits another
``max_in_flight`` invocations (which FIFO result delivery enforces) can
never observe its aliased array being clobbered.

True chip-to-chip movement with NO host involvement belongs INSIDE a jit
program over a mesh (ppermute/collectives — see ray_tpu.parallel); that is
the TPU-idiomatic fast path the reference's NCCL channels approximate from
the outside.
"""

from __future__ import annotations

import pickle
import struct
from typing import Any, Optional

from ray_tpu.experimental.channel import Channel

_KIND_PICKLE = 0
_KIND_TENSOR = 1
_KIND_META_TENSOR = 2
_PREFIX = struct.Struct("<BIH")  # (kind, header_size, body_pad)
_ALIGN = 64  # body alignment: unaligned buffers force jax to copy on import


class TensorWithMeta:
    """Channel payload pairing a small picklable ``meta`` dict with ONE
    host tensor whose bytes ride the ring slot RAW (64B-aligned body,
    like the bare-tensor kind) — the KV-block shipping shape (ISSUE 13):
    meta carries request identity/geometry, the tensor carries the block
    batch, and neither side ever pickles the tensor body. The reader
    gets the array as a COPY (ring backpressure protects aliased reads
    only while the value is being consumed in-stage; KV adoption defers
    the device scatter to the decode engine's loop thread, which may run
    after this reader advances past ``nslots`` more values)."""

    __slots__ = ("meta", "tensor")

    def __init__(self, meta: dict, tensor):
        self.meta = meta
        self.tensor = tensor


class DeviceTensorType:
    """Edge type hint: the value is a jax array to ship device-to-device
    (reference ``TorchTensorType`` role)."""

    def __init__(self, device: Optional[str] = None):
        self.device = device  # None -> consumer's default device

    def __repr__(self):
        return f"DeviceTensorType(device={self.device!r})"


def _is_jax_array(value) -> bool:
    import sys

    jnp_mod = sys.modules.get("jax")
    return jnp_mod is not None and isinstance(value, jnp_mod.Array)


class DeviceChannel(Channel):
    """Channel whose payloads are jax arrays moved as raw device bytes."""

    def _encode(self, value: Any):
        if isinstance(value, TensorWithMeta):
            import numpy as np

            host = np.asarray(value.tensor)
            # the dtype OBJECT, not dtype.str: extension dtypes
            # (ml_dtypes bfloat16 — the KV payload dtype) stringify to
            # an opaque void ("|V2") that cannot round-trip
            header = pickle.dumps((value.meta, host.dtype, host.shape))
            body = (host if host.flags["C_CONTIGUOUS"] else host.tobytes())
            return self._encode_parts(_KIND_META_TENSOR, header, body,
                                      host.nbytes)
        if not _is_jax_array(value):
            body = pickle.dumps(value)
            return self._encode_parts(_KIND_PICKLE, b"", body, len(body))
        import numpy as np

        host = np.asarray(value)  # D2H (CPU backend: view, no copy)
        header = pickle.dumps((host.dtype.str, host.shape))
        body = (host if host.flags["C_CONTIGUOUS"] else host.tobytes())
        return self._encode_parts(_KIND_TENSOR, header, body, host.nbytes)

    def _encode_parts(self, kind: int, header: bytes, body, nbytes: int):
        # pad so the body lands 64B-aligned in the mapped file regardless
        # of which slot it goes to (slot payload offsets are themselves
        # multiples of the slot stride; align relative to the file start
        # by padding to the next _ALIGN boundary past the headers)
        pad = (-(_PREFIX.size + len(header))) % _ALIGN
        total = _PREFIX.size + len(header) + pad + nbytes

        def fill(mm, off):
            import numpy as np

            _PREFIX.pack_into(mm, off, kind, len(header), pad)
            o = off + _PREFIX.size
            mm[o:o + len(header)] = header
            o += len(header) + pad
            view = np.frombuffer(mm, np.uint8, nbytes, o)
            if isinstance(body, (bytes, bytearray)):
                view[:] = np.frombuffer(body, np.uint8)
            else:
                view[:] = np.asarray(body, order="C").reshape(-1).view(
                    np.uint8)
            del view

        return total, fill

    def read(self, timeout: Optional[float] = None) -> Any:
        off, size = self._wait_slot(timeout)
        value = self._decode(off, size)
        self._advance()
        return value

    def _decode(self, off: int, size: int):
        import numpy as np

        kind, hsize, pad = _PREFIX.unpack_from(self._mm, off)
        o = off + _PREFIX.size
        header = bytes(self._mm[o:o + hsize])
        o += hsize + pad
        body_size = size - _PREFIX.size - hsize - pad
        if kind == _KIND_PICKLE:
            return pickle.loads(bytes(self._mm[o:o + body_size]))
        if kind == _KIND_META_TENSOR:
            meta, dtype_obj, shape = pickle.loads(header)
            dt = np.dtype(dtype_obj)
            view = np.frombuffer(self._mm, dt, body_size // dt.itemsize,
                                 o).reshape(shape)
            # copy out of the mapped slot: the consumer (KV adoption)
            # uses the array after this reader's cursor moves on
            return TensorWithMeta(meta, np.array(view))
        dtype_str, shape = pickle.loads(header)
        dtype = np.dtype(dtype_str)
        host = np.frombuffer(self._mm, dtype, body_size // dtype.itemsize,
                             o).reshape(shape)
        # the backend query below must honor JAX_PLATFORMS first: a
        # site-pinned TPU plugin would otherwise try to claim the chip from
        # a CPU worker and can hang when the tunnel is unclaimable
        from ray_tpu.util.tpu_info import honor_jax_platform_env

        honor_jax_platform_env()
        import jax

        if jax.default_backend() == "cpu":
            # zero-copy: the consumer jax array aliases the slot memory
            # (ring backpressure + FIFO-bounded admission mean the writer
            # cannot clobber this slot while a correctly-driven DAG stage
            # still uses the value — see module docstring)
            try:
                return jax.dlpack.from_dlpack(host)
            except Exception:
                pass
        return jax.device_put(host)  # one H2D DMA on accelerators

    def __reduce__(self):
        return (_attach_device_channel, (self.name,))


def _attach_device_channel(name: str) -> "DeviceChannel":
    return DeviceChannel(name, create=False)
