"""Device-tensor channels: the compiled-DAG accelerator data plane.

Role analog: the reference's NCCL channels for DAG edges
(``python/ray/experimental/channel/torch_tensor_nccl_channel.py:29``,
``nccl_group.py:18``) typed by ``TorchTensorType``
(``torch_tensor_type.py``). TPU-native shape of the idea:

- an edge annotated :class:`DeviceTensorType` carries ONE jax array whose
  payload bytes move through the channel's shm segment RAW (dtype/shape in
  a tiny header) instead of the generic pickle path;
- the reader materializes a ``jax.Array`` straight from the mapped segment:
  zero-copy via dlpack on host-mapped backends (CPU — the consumer array
  aliases the channel buffer, no copy at all), one H2D DMA on TPU
  (``jax.device_put``; cross-process device memory can't be shared through
  host shm, so one hop is the floor — the reference pays the same in NCCL
  as a D2D hop);
- non-tensor control values (teardown/error sentinels) fall back to the
  pickle path transparently.

True chip-to-chip movement with NO host involvement belongs INSIDE a jit
program over a mesh (ppermute/collectives — see ray_tpu.parallel); that is
the TPU-idiomatic fast path the reference's NCCL channels approximate from
the outside.
"""

from __future__ import annotations

import pickle
import struct
import time
from typing import Any, Optional

from ray_tpu.core import serialization
from ray_tpu.experimental.channel import (
    Channel,
    ChannelFullError,
    ChannelTimeoutError,
    _HEADER,
    _SEQ,
)

_KIND_PICKLE = 0
_KIND_TENSOR = 1
_PREFIX = struct.Struct("<BIH")  # (kind, header_size, body_pad)
_ALIGN = 64  # body alignment: unaligned buffers force jax to copy on import


class DeviceTensorType:
    """Edge type hint: the value is a jax array to ship device-to-device
    (reference ``TorchTensorType`` role)."""

    def __init__(self, device: Optional[str] = None):
        self.device = device  # None -> consumer's default device

    def __repr__(self):
        return f"DeviceTensorType(device={self.device!r})"


def _is_jax_array(value) -> bool:
    import sys

    jnp_mod = sys.modules.get("jax")
    return jnp_mod is not None and isinstance(value, jnp_mod.Array)


class DeviceChannel(Channel):
    """Channel whose payloads are jax arrays moved as raw device bytes."""

    def write(self, value: Any) -> None:
        if not _is_jax_array(value):
            return self._write_parts(
                _KIND_PICKLE, b"", pickle.dumps(value))
        import numpy as np

        host = np.asarray(value)  # D2H (CPU backend: view, no copy)
        header = pickle.dumps((host.dtype.str, host.shape))
        return self._write_parts(_KIND_TENSOR, header,
                                 host.tobytes() if not host.flags["C_CONTIGUOUS"]
                                 else host, nbytes=host.nbytes)

    def _write_parts(self, kind: int, header: bytes, body,
                     nbytes: Optional[int] = None) -> None:
        import numpy as np

        nbytes = len(body) if nbytes is None else nbytes
        pad = (-(_HEADER.size + _PREFIX.size + len(header))) % _ALIGN
        total = _PREFIX.size + len(header) + pad + nbytes
        if total > self.capacity:
            raise ChannelFullError(
                f"payload {total}B exceeds channel capacity {self.capacity}B")
        seq, _ = _HEADER.unpack_from(self._mm, 0)
        _SEQ.pack_into(self._mm, 0, seq + 1)               # odd: writing
        _SEQ.pack_into(self._mm, 8, total)
        off = _HEADER.size
        _PREFIX.pack_into(self._mm, off, kind, len(header), pad)
        off += _PREFIX.size
        self._mm[off:off + len(header)] = header
        off += len(header) + pad
        view = np.frombuffer(self._mm, np.uint8, nbytes, off)
        if isinstance(body, (bytes, bytearray)):
            view[:] = np.frombuffer(body, np.uint8)
        else:
            view[:] = np.asarray(body, order="C").reshape(-1).view(np.uint8)
        del view
        _SEQ.pack_into(self._mm, 0, seq + 2)               # even: ready

    def read(self, timeout: Optional[float] = None) -> Any:
        deadline = None if timeout is None else time.monotonic() + timeout
        spins = 0
        while True:
            seq, size = _HEADER.unpack_from(self._mm, 0)
            if seq % 2 == 0 and seq > self._last_read_seq:
                value = self._decode(size)
                seq2, _ = _HEADER.unpack_from(self._mm, 0)
                if seq2 == seq:          # seqlock validate
                    self._last_read_seq = seq
                    return value
            spins += 1
            if spins < 1000:
                continue
            if deadline is not None and time.monotonic() > deadline:
                raise ChannelTimeoutError(
                    f"channel {self.name} read timed out after {timeout}s")
            time.sleep(0.0002)

    def _decode(self, size: int):
        import numpy as np

        off = _HEADER.size
        kind, hsize, pad = _PREFIX.unpack_from(self._mm, off)
        off += _PREFIX.size
        header = bytes(self._mm[off:off + hsize])
        off += hsize + pad
        body_size = size - _PREFIX.size - hsize - pad
        if kind == _KIND_PICKLE:
            return pickle.loads(bytes(self._mm[off:off + body_size]))
        dtype_str, shape = pickle.loads(header)
        dtype = np.dtype(dtype_str)
        host = np.frombuffer(self._mm, dtype, body_size // dtype.itemsize,
                             off).reshape(shape)
        # the backend query below must honor JAX_PLATFORMS first: a
        # site-pinned TPU plugin would otherwise try to claim the chip from
        # a CPU worker and can hang when the tunnel is unclaimable
        from ray_tpu.util.tpu_info import honor_jax_platform_env

        honor_jax_platform_env()
        import jax

        if jax.default_backend() == "cpu":
            # zero-copy: the consumer jax array aliases the channel segment
            # (single-slot channels are consume-before-next-write, so the
            # writer cannot clobber a value the reader is still using in a
            # correctly-driven DAG)
            try:
                return jax.dlpack.from_dlpack(host)
            except Exception:
                pass
        return jax.device_put(host)  # one H2D DMA on accelerators

    def __reduce__(self):
        return (_attach_device_channel, (self.name,))


def _attach_device_channel(name: str) -> "DeviceChannel":
    return DeviceChannel(name, create=False)
