"""Experimental: mutable channels (compiled-DAG data plane)."""

from ray_tpu.experimental.channel import (
    Channel,
    ChannelFullError,
    ChannelTimeoutError,
)

__all__ = ["Channel", "ChannelFullError", "ChannelTimeoutError"]
