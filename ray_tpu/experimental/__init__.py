"""Experimental: mutable channels (compiled-DAG data plane) + broadcast."""

from ray_tpu.experimental.channel import (
    Channel,
    ChannelFullError,
    ChannelTimeoutError,
)


def broadcast_object(ref, node_ids=None) -> int:
    """Proactively replicate ``ref``'s object to cluster nodes via a relay
    tree (reference PushManager role, ``push_manager.h:30``): each receiver
    re-serves its subtree, so no single owner uploads N copies. Default
    targets: every alive node not already holding the object. Returns the
    number of nodes targeted; 0 in local mode or for inline objects."""
    from ray_tpu.core.runtime import _get_runtime

    rt = _get_runtime()
    if rt.cluster is None:
        return 0
    return rt.cluster.broadcast_object(ref.id.binary(), node_ids)


__all__ = ["Channel", "ChannelFullError", "ChannelTimeoutError",
           "broadcast_object"]
