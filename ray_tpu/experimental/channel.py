"""Mutable shared-memory channels: the compiled-DAG data plane.

Role analog: the reference's mutable plasma objects backing accelerated
DAGs (``src/ray/core_worker/experimental_mutable_object_manager.h:37`` +
``python/ray/experimental/channel/shared_memory_channel.py``). A channel is
one fixed-capacity shm segment reused for every DAG invocation — no
per-call allocation, no scheduler on the data path.

Synchronization is a seqlock: the writer bumps the sequence to odd, writes
payload, bumps to even; a reader waits for an even sequence greater than
the last it consumed, reads, and validates the sequence didn't move.
Polling backs off from spin to short sleeps (the reference blocks on
futexes in plasma; cross-process futex on shm is overkill at these
latencies).
"""

from __future__ import annotations

import mmap
import os
import struct
import time
from typing import Any, Optional

from ray_tpu.core import serialization

_HEADER = struct.Struct("<QQ")  # (seq, payload_size)
_SEQ = struct.Struct("<Q")
_SHM_DIR = "/dev/shm"


class ChannelFullError(RuntimeError):
    pass


class ChannelTimeoutError(TimeoutError):
    pass


class Channel:
    """Single-writer multi-reader mutable shm channel."""

    def __init__(self, name: str, capacity: int = 1 << 20,
                 create: bool = False):
        self.name = name
        self.path = os.path.join(_SHM_DIR, f"rtpu-chan-{name}")
        self.capacity = capacity
        if create:
            fd = os.open(self.path, os.O_CREAT | os.O_RDWR, 0o600)
            try:
                os.ftruncate(fd, _HEADER.size + capacity)
                self._mm = mmap.mmap(fd, _HEADER.size + capacity)
            finally:
                os.close(fd)
            _HEADER.pack_into(self._mm, 0, 0, 0)
        else:
            # attach: wait briefly for the creator
            deadline = time.monotonic() + 10.0
            while True:
                try:
                    fd = os.open(self.path, os.O_RDWR)
                    break
                except FileNotFoundError:
                    if time.monotonic() > deadline:
                        raise
                    time.sleep(0.001)
            try:
                size = os.fstat(fd).st_size
                self._mm = mmap.mmap(fd, size)
                self.capacity = size - _HEADER.size
            finally:
                os.close(fd)
        self._last_read_seq = 0

    # -- writer -----------------------------------------------------------

    def write(self, value: Any) -> None:
        data, buffers = serialization.serialize(value)
        size = serialization.serialized_size(data, buffers)
        if size > self.capacity:
            raise ChannelFullError(
                f"payload {size}B exceeds channel capacity {self.capacity}B")
        seq, _ = _HEADER.unpack_from(self._mm, 0)
        # Seqlock publish order matters: odd seq FIRST (readers back off),
        # then size+payload, then even seq. Writing size together with the
        # old even seq would let a reader pair a stale sequence with the
        # new size and accept a torn payload.
        _SEQ.pack_into(self._mm, 0, seq + 1)               # odd: writing
        _SEQ.pack_into(self._mm, 8, size)
        serialization.write_into(
            memoryview(self._mm)[_HEADER.size:_HEADER.size + size],
            data, buffers)
        _SEQ.pack_into(self._mm, 0, seq + 2)               # even: ready

    # -- reader -----------------------------------------------------------

    def read(self, timeout: Optional[float] = None) -> Any:
        """Block until a value newer than the last read is available."""
        deadline = None if timeout is None else time.monotonic() + timeout
        spins = 0
        while True:
            seq, size = _HEADER.unpack_from(self._mm, 0)
            if seq % 2 == 0 and seq > self._last_read_seq:
                payload = bytes(
                    self._mm[_HEADER.size:_HEADER.size + size])
                seq2, _ = _HEADER.unpack_from(self._mm, 0)
                if seq2 == seq:          # seqlock validate
                    self._last_read_seq = seq
                    return serialization.read_from(memoryview(payload))
            spins += 1
            if spins < 1000:
                continue
            if deadline is not None and time.monotonic() > deadline:
                raise ChannelTimeoutError(
                    f"channel {self.name} read timed out after {timeout}s")
            time.sleep(0.0002)

    def poll(self) -> bool:
        seq, _ = _HEADER.unpack_from(self._mm, 0)
        return seq % 2 == 0 and seq > self._last_read_seq

    def close(self) -> None:
        try:
            self._mm.close()
        except (BufferError, ValueError):
            pass

    def unlink(self) -> None:
        self.close()
        try:
            os.unlink(self.path)
        except FileNotFoundError:
            pass

    def __reduce__(self):
        # channels travel to actors by name; they attach on arrival
        return (_attach_channel, (self.name,))


def _attach_channel(name: str) -> "Channel":
    return Channel(name, create=False)
