"""Mutable shared-memory ring channels: the compiled-DAG data plane.

Role analog: the reference's mutable plasma objects backing accelerated
DAGs (``src/ray/core_worker/experimental_mutable_object_manager.h:37`` +
``python/ray/experimental/channel/shared_memory_channel.py``). A channel
is one fixed shm segment reused for every DAG invocation — no per-call
allocation, no scheduler on the data path.

r13 pipelining rewrite: the single value slot became a bounded RING of
``slots`` seq-numbered slots, so ``slots - 1`` DAG invocations can be in
flight at once (the reference's ``max_buffered_results`` role). Layout::

    header   write_seq | nslots | slot_size | nreaders
    cursors  reader_cursor[_MAX_READERS]     (values consumed per reader)
    slots    nslots x (slot_seq | size | payload[slot_size])

Synchronization stays lock-free:

- ONE writer publishes value ``k`` (0-based) into slot ``k % nslots``:
  invalidate the slot's seq, write size+payload, publish ``seq = k + 1``.
- Readers register a shm cursor once (flock-serialized) and then wait for
  slot ``r % nslots`` to carry ``seq == r + 1``; consuming advances the
  cursor — a single aligned 8-byte store.
- Backpressure: the writer blocks (bounded) while
  ``write_seq - min(reader cursors) >= nslots`` — it can never lap an
  unconsumed value, which is also what makes the device channel's
  zero-copy reads safe under pipelining.

Polling backs off from spin to short sleeps (the reference blocks on
futexes in plasma; cross-process futex on shm is overkill at these
latencies). Waits that actually back off feed the
``rtpu_channel_{read,write}_wait_seconds`` histograms.
"""

from __future__ import annotations

import mmap
import os
import struct
import time
from typing import Any, Optional

from ray_tpu.core import serialization

_HDR = struct.Struct("<QQQQ")   # (write_seq, nslots, slot_size, nreaders)
_U64 = struct.Struct("<Q")
_SLOT_HDR = struct.Struct("<QQ")  # (slot_seq = 1-based value index, size)
_MAX_READERS = 8
_CURSORS_OFF = _HDR.size
# slots start 64-aligned and each slot's PAYLOAD starts 64 bytes past the
# slot header, with slot sizes rounded to 64 — so payload offsets are
# 64-aligned in the file for every slot (the device channel aligns tensor
# bodies absolutely; unaligned buffers force jax to copy on import)
_SLOTS_OFF = 128
_SLOT_PAYLOAD_OFF = 64
#: cursor sentinel a closing reader stores so it stops gating the writer
_DETACHED = (1 << 64) - 1
_SHM_DIR = "/dev/shm"

# lazily-bound wait histograms (defs in util/metric_defs); never allowed
# to fail a channel op, observed only when a wait actually backed off
_m = {"read": None, "write": None}


def _observe_wait(kind: str, seconds: float) -> None:
    try:
        m = _m[kind]
        if m is None:
            from ray_tpu.util import metric_defs

            m = _m[kind] = metric_defs.get(
                f"rtpu_channel_{kind}_wait_seconds")
        m.observe(seconds)
    except Exception:
        pass


class ChannelFullError(RuntimeError):
    pass


class ChannelTimeoutError(TimeoutError):
    pass


class Channel:
    """Single-writer multi-reader mutable shm ring channel."""

    def __init__(self, name: str, capacity: int = 1 << 20,
                 create: bool = False, slots: int = 2):
        self.name = name
        self.path = os.path.join(_SHM_DIR, f"rtpu-chan-{name}")
        if create:
            if slots < 1:
                raise ValueError("channel needs at least one slot")
            self.nslots = int(slots)
            self.slot_size = (int(capacity) + 63) // 64 * 64
            total = _SLOTS_OFF + self.nslots * (_SLOT_PAYLOAD_OFF
                                                + self.slot_size)
            fd = os.open(self.path, os.O_CREAT | os.O_RDWR, 0o600)
            try:
                os.ftruncate(fd, total)
                self._mm = mmap.mmap(fd, total)
            finally:
                os.close(fd)
            _HDR.pack_into(self._mm, 0, 0, self.nslots, self.slot_size, 0)
        else:
            # attach: wait briefly for the creator
            deadline = time.monotonic() + 10.0
            while True:
                try:
                    fd = os.open(self.path, os.O_RDWR)
                    break
                except FileNotFoundError:
                    if time.monotonic() > deadline:
                        raise
                    time.sleep(0.001)
            try:
                size = os.fstat(fd).st_size
                self._mm = mmap.mmap(fd, size)
            finally:
                os.close(fd)
            _, self.nslots, self.slot_size, _ = _HDR.unpack_from(self._mm, 0)
        self.capacity = self.slot_size  # back-compat alias (per-value cap)
        self._stride = _SLOT_PAYLOAD_OFF + self.slot_size
        # values consumed by THIS handle; the shm cursor mirrors it once
        # the handle registers as a reader (lazily, on first read)
        self._cursor = 0
        self._reader_idx: Optional[int] = None

    # -- reader registration ---------------------------------------------

    def _register_reader(self) -> None:
        """Claim a shm cursor slot (flock-serialized; registration is a
        once-per-reader cold path). New readers start at cursor 0 and see
        the full un-lapped backlog — backpressure guarantees nothing they
        are entitled to was overwritten."""
        import fcntl

        fd = os.open(self.path, os.O_RDWR)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX)
            (n,) = _U64.unpack_from(self._mm, 24)
            if n >= _MAX_READERS:
                raise RuntimeError(
                    f"channel {self.name}: more than {_MAX_READERS} readers")
            _U64.pack_into(self._mm, _CURSORS_OFF + _U64.size * n,
                           self._cursor)
            _U64.pack_into(self._mm, 24, n + 1)
            self._reader_idx = n
        finally:
            os.close(fd)  # close releases the flock

    def _store_cursor(self) -> None:
        _U64.pack_into(self._mm, _CURSORS_OFF + _U64.size * self._reader_idx,
                       self._cursor)

    # -- writer -----------------------------------------------------------

    def _min_consumed(self) -> int:
        (n,) = _U64.unpack_from(self._mm, 24)
        if n == 0:
            return 0  # no reader yet: the ring itself is the only bound
        low = _DETACHED
        for i in range(n):
            (c,) = _U64.unpack_from(self._mm, _CURSORS_OFF + _U64.size * i)
            if c < low:
                low = c
        if low == _DETACHED:   # every reader detached: nothing gates us
            (seq,) = _U64.unpack_from(self._mm, 0)
            return seq
        return low

    def _wait_writable(self, seq: int, timeout: Optional[float]) -> None:
        if seq - self._min_consumed() < self.nslots:
            return
        t0 = time.monotonic()
        deadline = None if timeout is None else t0 + timeout
        while True:
            time.sleep(0.0002)
            if seq - self._min_consumed() < self.nslots:
                _observe_wait("write", time.monotonic() - t0)
                return
            if deadline is not None and time.monotonic() > deadline:
                _observe_wait("write", time.monotonic() - t0)
                raise ChannelFullError(
                    f"channel {self.name} ring full ({self.nslots} slots, "
                    f"slowest reader at {self._min_consumed()}) for "
                    f"{timeout}s")

    def write(self, value: Any, timeout: Optional[float] = None) -> None:
        """Publish the next value. Blocks while the ring is full (bounded
        by ``timeout``; ``None`` waits forever) — the writer never laps an
        unconsumed slot."""
        size, fill = self._encode(value)
        if size > self.slot_size:
            raise ChannelFullError(
                f"payload {size}B exceeds channel slot capacity "
                f"{self.slot_size}B")
        (seq,) = _U64.unpack_from(self._mm, 0)
        self._wait_writable(seq, timeout)
        off = _SLOTS_OFF + (seq % self.nslots) * self._stride
        # publish order matters: invalidate the slot FIRST (readers back
        # off), then size+payload, then the new slot seq
        _SLOT_HDR.pack_into(self._mm, off, 0, size)
        fill(self._mm, off + _SLOT_PAYLOAD_OFF)
        _U64.pack_into(self._mm, off, seq + 1)
        _U64.pack_into(self._mm, 0, seq + 1)

    def _encode(self, value: Any):
        """(size, fill(mm, off)) for the generic pickle payload; the
        device channel overrides this with the raw-tensor layout."""
        data, buffers = serialization.serialize(value)
        size = serialization.serialized_size(data, buffers)

        def fill(mm, off):
            serialization.write_into(
                memoryview(mm)[off:off + size], data, buffers)

        return size, fill

    # -- reader -----------------------------------------------------------

    def _wait_slot(self, timeout: Optional[float]):
        """Block until the next unconsumed value is published; returns
        (payload_offset, size). Registers this handle's shm cursor on
        first use."""
        if self._reader_idx is None:
            self._register_reader()
        expect = self._cursor + 1
        off = _SLOTS_OFF + (self._cursor % self.nslots) * self._stride
        deadline = None if timeout is None else time.monotonic() + timeout
        spins = 0
        t0 = 0.0
        while True:
            sseq, size = _SLOT_HDR.unpack_from(self._mm, off)
            if sseq == expect:
                if t0:
                    _observe_wait("read", time.monotonic() - t0)
                return off + _SLOT_PAYLOAD_OFF, size
            spins += 1
            if spins < 1000:
                continue
            if not t0:
                t0 = time.monotonic()
            if deadline is not None and time.monotonic() > deadline:
                _observe_wait("read", time.monotonic() - t0)
                raise ChannelTimeoutError(
                    f"channel {self.name} read timed out after {timeout}s")
            time.sleep(0.0002)

    def _advance(self) -> None:
        self._cursor += 1
        self._store_cursor()

    def read(self, timeout: Optional[float] = None) -> Any:
        """Block until a value newer than the last read is available."""
        off, size = self._wait_slot(timeout)
        # copy BEFORE deserializing: backpressure means the writer cannot
        # overwrite an unconsumed slot, so no seqlock re-validation is
        # needed — the copy just keeps the deserializer off live shm
        payload = bytes(self._mm[off:off + size])
        value = serialization.read_from(memoryview(payload))
        self._advance()
        return value

    def poll(self) -> bool:
        off = _SLOTS_OFF + (self._cursor % self.nslots) * self._stride
        (sseq,) = _U64.unpack_from(self._mm, off)
        return sseq == self._cursor + 1

    def close(self) -> None:
        if self._reader_idx is not None:
            try:
                # stop gating the writer: a closed reader's cursor parks
                # at the detached sentinel
                _U64.pack_into(self._mm,
                               _CURSORS_OFF + _U64.size * self._reader_idx,
                               _DETACHED)
            except (ValueError, IndexError):
                pass
            self._reader_idx = None
        try:
            self._mm.close()
        except (BufferError, ValueError):
            pass

    def unlink(self) -> None:
        self.close()
        try:
            os.unlink(self.path)
        except FileNotFoundError:
            pass

    def __reduce__(self):
        # channels travel to actors by name; they attach on arrival
        return (_attach_channel, (self.name,))


def _attach_channel(name: str) -> "Channel":
    return Channel(name, create=False)
