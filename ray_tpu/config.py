"""Central configuration registry — every runtime knob in ONE table.

Role analog: reference ``src/ray/common/ray_config_def.h`` (217
``RAY_CONFIG(type, name, default)`` entries, each overridable via a
``RAY_<name>`` env var, parsed in ``ray_config.h``). Here every knob is
registered with its type, default, and doc; the value is resolved from the
``RTPU_<NAME>`` environment variable LAZILY on each access, so tests that
``monkeypatch.setenv`` before booting a subsystem keep working and
subprocess workers inherit overrides through the environment — the same
property the reference gets from parsing env vars at RayConfig init in
every process.

Usage::

    from ray_tpu import config
    grace = config.get("gcs_free_grace_s")      # float, env-overridable
    rows  = config.describe()                    # table for CLI / docs

CLI: ``ray_tpu config`` prints the table with any non-default values
highlighted.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, NamedTuple


class Knob(NamedTuple):
    name: str           # registry key; env var is RTPU_<NAME.upper()>
    type: Callable      # parser applied to the env string
    default: Any
    doc: str
    where: str          # module that consumes it


def _bool(s: str) -> bool:
    return s.strip().lower() not in ("0", "false", "no", "off", "")


_REGISTRY: Dict[str, Knob] = {}


def _knob(name: str, type_: Callable, default: Any, doc: str,
          where: str) -> None:
    assert name not in _REGISTRY, f"duplicate knob {name}"
    _REGISTRY[name] = Knob(name, type_, default, doc, where)


# -- core runtime -----------------------------------------------------------
_knob("worker_start_timeout", float, 120.0,
      "seconds to wait for a spawned worker to dial back before declaring "
      "it failed", "train/backend_executor.py")
_knob("log_to_driver", _bool, True,
      "stream worker stdout/stderr lines to the driver's console",
      "core/runtime.py")
_knob("memory_monitor", _bool, True,
      "enable the host-RAM OOM monitor (kills retriable tasks first; "
      "reference MemoryMonitor + worker-killing policies)",
      "core/runtime.py")
_knob("memory_usage_threshold", float, 0.95,
      "host memory fraction above which the OOM policy starts killing",
      "core/runtime.py")
_knob("lineage_max", int, 100_000,
      "max task specs retained for object reconstruction (reference "
      "lineage cap role)", "core/runtime.py")
_knob("lineage_max_bytes", int, 512 << 20,
      "byte bound on retained lineage (inlined args dominate; reference "
      "RAY_max_lineage_bytes)", "core/runtime.py")

_knob("worker_zygote", _bool, True,
      "spawn workers by forking a pre-warmed single-threaded fork-server "
      "(~5ms) instead of exec'ing a fresh interpreter (~0.15s); the "
      "fork-server never imports jax or user code", "core/runtime.py")
_knob("pipe_coalesce_us", int, 200,
      "Nagle-style flush window (microseconds) for worker->driver cast "
      "coalescing: fire-and-forget casts (submit, refpin, put, metric "
      "pushes) buffer up to this long and ship as ONE framed batch, and "
      "every latency-sensitive send (done/req) piggybacks the pending "
      "casts in its own frame; 0 disables buffering (casts still "
      "piggyback)", "core/worker.py")
_knob("dag_max_in_flight", int, 8,
      "default overlapping invocations a compiled DAG admits "
      "(ring-channel slots = max_in_flight + 1)", "dag/compiled_dag.py")
_knob("native_pipe", _bool, True,
      "drive each worker control pipe through the GIL-free C++ engine "
      "(framing, batch pack/unpack, send coalescing and refpin "
      "bookkeeping run in native threads; falls back to the Python "
      "reader/sender when the .so is missing or stale)",
      "core/runtime.py")
_knob("pipe_native_coalesce_us", int, 0,
      "optional Nagle window for the NATIVE driver->worker sender; 0 "
      "(default) relies on natural coalescing — everything enqueued "
      "while the previous write was in flight ships as one batch frame",
      "core/runtime.py")

# -- object store -----------------------------------------------------------
_knob("native_store", _bool, True,
      "use the C++ shm arena (falls back to file-per-object segments)",
      "core/object_store.py")
_knob("store_capacity", int, 1 << 30,
      "shm arena capacity in bytes per node", "core/object_store.py")
_knob("spill_threshold", int, 4 << 30,
      "total shm bytes after which big objects spill to disk",
      "core/object_store.py")
_knob("spill_restore", _bool, True,
      "promote spilled objects back into shm on access when headroom "
      "allows (reference LocalObjectManager restore role)",
      "core/object_store.py")
_knob("store_prefault_bytes", str, str(512 << 20),
      "arena head bytes prefaulted in the background at boot (first-touch "
      "page faults cap cold tmpfs writes at ~2 GB/s on this class of box "
      "vs ~7.5 GB/s warm); '0' disables, 'all' populates the whole arena",
      "_native/__init__.py")
_knob("store_parallel_copy_bytes", int, 4 << 20,
      "payload size at or above which store writes/reads use the native "
      "multi-threaded memcpy (N slicing threads, GIL released); 0 "
      "disables the parallel path", "core/serialization.py")
_knob("store_copy_threads", int, 0,
      "threads for the parallel memcpy path (0 = auto: hardware "
      "concurrency, capped at 8)", "core/serialization.py")
_knob("spill_compression", str, "auto",
      "codec for the disk spill path: auto (native lz4, zlib when the "
      ".so is unavailable) | lz4 | zlib | off. Files carry a "
      "self-describing header; readers handle every codec plus legacy "
      "raw files", "core/spill_codec.py")
_knob("spill_compress_max_bytes", int, 512 << 20,
      "objects larger than this spill RAW (mmap-servable): a compressed "
      "spill read with no shm headroom must inflate to heap, so the cap "
      "bounds that worst case; 0 = compress everything",
      "core/spill_codec.py")

# -- cluster ----------------------------------------------------------------
_knob("gcs_max_objects", int, 200_000,
      "directory entry cap; terminal unpinned entries past it are evicted",
      "cluster/gcs_server.py")
_knob("gcs_evict_min_age_s", float, 30.0,
      "min seconds after terminal before an unpinned entry may be evicted",
      "cluster/gcs_server.py")
_knob("gcs_free_grace_s", float, 10.0,
      "grace between refcount-zero and freeing (an in-flight pin on "
      "another connection may still land)", "cluster/gcs_server.py")
_knob("gcs_max_task_events", int, 50_000,
      "cluster-wide task event buffer size (reference GcsTaskManager "
      "store)", "cluster/gcs_server.py")
_knob("rpc_default_timeout_s", float, 60.0,
      "deadline applied to cluster RPC call() when the caller passes no "
      "timeout — a wedged peer must surface TimeoutError, never block a "
      "thread forever (generous: 2-vCPU CI boxes stall for seconds under "
      "load); <= 0 restores the unbounded wait", "cluster/rpc.py")
_knob("pull_chunk_bytes", int, 4 << 20,
      "chunk size for node-to-node object transfer",
      "cluster/adapter.py")
_knob("pull_concurrency", int, 2,
      "max concurrent big-object pulls per node (admission control, "
      "reference PullManager role)", "cluster/adapter.py")
_knob("pull_parallel", int, 2,
      "chunk-fetch threads per big-object pull (chunks of one object "
      "stream concurrently over the peer RPC into disjoint offsets of "
      "the preallocated segment); 1 = serial", "cluster/adapter.py")
_knob("locality_min_bytes", int, 1 << 20,
      "objects at least this big attract dependency-locality placement",
      "cluster/adapter.py")
_knob("hybrid_threshold", float, 0.5,
      "hybrid scheduling: pack until a node passes this utilization, then "
      "spread (reference hybrid_scheduling_policy.h)",
      "cluster/adapter.py")

# -- data (streaming exchange) ----------------------------------------------
_knob("data_streaming_exchange", _bool, True,
      "run Data all-to-all ops (sort/shuffle/repartition/groupby) through "
      "the streaming exchange engine; off = legacy one-shot task exchange",
      "data/streaming.py")
_knob("data_exchange_reducers", int, 4,
      "max reducer actors per streaming exchange (logical partitions are "
      "multiplexed over them)", "data/streaming.py")
_knob("data_exchange_inflight", int, 32,
      "max exchange blocks in flight (partition outputs not yet consumed "
      "by a reducer) — the engine's backpressure bound",
      "data/streaming.py")
_knob("data_exchange_run_bytes", int, 32 << 20,
      "reducer buffer bytes before a sorted run is flushed to the object "
      "store (external-sort run size)", "data/streaming.py")
_knob("data_exchange_target_rows", int, 250_000,
      "rows per output block emitted by a streaming reducer",
      "data/streaming.py")
_knob("data_exchange_retries", int, 2,
      "times a Dataset plan re-executes from lineage (sources are never "
      "freed) when a streaming-exchange reducer actor dies before any "
      "output was consumed; 0 = surface ActorDiedError", "data/dataset.py")

# -- ops / models -----------------------------------------------------------
_knob("attn_impl", str, "",
      "force the attention kernel: pallas | xla | naive (empty = auto)",
      "ops/attention.py")

# -- observability ----------------------------------------------------------
_knob("metrics_federation", _bool, True,
      "federate per-process metric registries to the head /metrics "
      "endpoint (workers push deltas over the control pipe; nodes ride "
      "the GCS heartbeat)", "util/metrics.py")
_knob("metrics_push_interval_s", float, 2.0,
      "min seconds between a worker's batched metric-delta pushes over "
      "the control pipe (<= 0 disables the push)", "core/worker.py")
_knob("contention_profiler", _bool, True,
      "instrument the runtime's hot locks (driver dispatch/ref locks, "
      "GCS state lock) with wait-time accounting: rtpu_lock_wait_seconds "
      "histograms + state.summarize_contention(); off = raw locks, zero "
      "overhead", "util/contention.py")
_knob("flight_recorder", _bool, True,
      "record per-task lifecycle phases (worker-side timing, driver "
      "histograms/ring, nested timeline slices); off = zero per-task "
      "telemetry cost", "core/runtime.py")
_knob("task_ring", int, 2048,
      "recent task lifecycle records kept in the driver's flight-recorder "
      "ring (feeds state.summarize_tasks per-phase percentiles)",
      "core/runtime.py")
_knob("trace_ring", int, 8192,
      "per-process span ring capacity (trace plane recording side); "
      "overflow before collection drops the oldest span and counts "
      "rtpu_trace_spans_dropped_total", "util/tracing.py")
_knob("trace_push_interval_s", float, 1.0,
      "min seconds between a worker's batched span pushes over the "
      "control pipe (the trace twin of metrics_push_interval_s)",
      "core/worker.py")
_knob("trace_store_max", int, 65536,
      "spans retained by a runtime's TraceStore (head query surface; "
      "daemons buffer here between heartbeats)", "util/trace_store.py")
_knob("gcs_max_trace_events", int, 65536,
      "cluster-wide span buffer size in the GCS (trace twin of "
      "gcs_max_task_events)", "cluster/gcs_server.py")
_knob("profile_hz", float, 67.0,
      "sampling-profiler frequency per process when armed "
      "(RTPU_PROFILING); the sampler walks sys._current_frames at this "
      "rate", "util/profiling.py")
_knob("profile_table_max", int, 4096,
      "max unique (thread, stack) keys aggregated per process between "
      "collection drains; overflow drops new stacks and counts "
      "rtpu_profile_samples_dropped_total", "util/profiling.py")
_knob("profile_push_interval_s", float, 1.0,
      "min seconds between a worker's batched profile pushes over the "
      "control pipe (the profile twin of trace_push_interval_s)",
      "core/worker.py")
_knob("profile_store_max", int, 2048,
      "profile batches retained by a runtime's ProfileStore (head query "
      "surface; daemons buffer here between heartbeats)",
      "util/profiling.py")
_knob("gcs_max_profile_events", int, 4096,
      "cluster-wide profile-batch buffer size in the GCS (profile twin "
      "of gcs_max_trace_events)", "cluster/gcs_server.py")
_knob("event_ring", int, 2048,
      "per-process lifecycle-event ring capacity (event plane recording "
      "side); overflow before collection drops the oldest event and "
      "counts rtpu_lifecycle_events_dropped_total", "util/events.py")
_knob("event_push_interval_s", float, 1.0,
      "min seconds between a worker's batched lifecycle-event pushes "
      "over the control pipe (the event twin of trace_push_interval_s)",
      "core/worker.py")
_knob("event_store_max", int, 16384,
      "lifecycle events retained by a runtime's EventStore (head query "
      "surface; daemons buffer here between heartbeats)",
      "util/event_store.py")
_knob("gcs_max_lifecycle_events", int, 16384,
      "cluster-wide lifecycle-event buffer size in the GCS (event twin "
      "of gcs_max_trace_events)", "cluster/gcs_server.py")
_knob("device_push_interval_s", float, 2.0,
      "min seconds between a worker's compiled-program-registry "
      "snapshot pushes over the control pipe (version-gated: nothing "
      "ships unless a compile bumped the registry)", "core/worker.py")
_knob("alerts_interval_s", float, 5.0,
      "watchdog evaluation period for the declarative alert rules at "
      "the head (RTPU_ALERTS=0 kills the watchdog outright)",
      "util/alerts.py")
_knob("log_tail_bytes", int, 16384,
      "max bytes of one log file shipped per cluster-wide log fetch "
      "(`rtpu logs` / /api/logs); postmortem stderr tails use a smaller "
      "fixed bound", "util/events.py")
_knob("obj_meta_max", int, 100_000,
      "object creation-metadata entries (owner/age/call-site) kept by "
      "the driver for `ray_tpu memory` forensics", "core/runtime.py")

# -- serve ------------------------------------------------------------------
_knob("serve_max_body", int, 64 << 20,
      "max HTTP request body bytes accepted by the serve proxy",
      "serve/proxy.py")
_knob("serve_request_retries", int, 3,
      "times a DeploymentHandle re-routes one request after the replica "
      "it was sent to died (each retry reports the death so the "
      "controller replaces the replica); 0 = surface ActorDiedError",
      "serve/handle.py")
_knob("serve_routing", str, "p2c",
      "replica picker: p2c (power-of-two-choices over queue depth + "
      "advertised free KV blocks) | rr (round-robin; the bench A/B "
      "baseline)", "serve/handle.py")
_knob("serve_kv_route_weight", float, 4.0,
      "routing-score weight of KV occupancy: score = queue_depth + "
      "weight * kv_used_fraction for replicas that advertise KV state; "
      "0 ignores KV pressure", "serve/handle.py")
_knob("serve_load_report_interval_s", float, 0.5,
      "cadence of a replica's load-state push to the controller (KV "
      "blocks free/total, in-flight requests) when its deployment "
      "exposes load_state(); <= 0 disables the push loop",
      "serve/replica.py")
_knob("serve_prefill_nice", int, 10,
      "niceness applied to a prefill-role replica's engine step loop: "
      "prefill is throughput-bound, decode is latency-bound, so on "
      "shared-core hosts long prefill bursts soak idle cycles instead "
      "of preempting decode cadence (on a real accelerator the step "
      "blocks on the device, so this is free); 0 disables",
      "serve/llm.py")
_knob("serve_model_budget_bytes", int, 0,
      "per-replica resident-weight budget for model multiplexing: the "
      "ModelRegistry LRU-evicts unpinned models past this many bytes of "
      "materialized params (in-flight requests pin their model); 0 = "
      "unbounded", "serve/multiplex.py")
_knob("serve_model_route_weight", float, 4.0,
      "routing-score penalty a DeploymentHandle adds to replicas that "
      "do NOT advertise the request's model_id as resident (a swap-in "
      "costs a weight page-in; 0 ignores residency)", "serve/handle.py")
_knob("serve_prefix_affinity", _bool, True,
      "route requests whose first prompt block matches a replica's "
      "published prefix digest to THAT replica (cluster-wide prefix "
      "affinity); off = plain p2c", "serve/handle.py")
_knob("serve_prefix_affinity_margin", float, 6.0,
      "max routing-score gap by which the prefix-affine replica may "
      "LOSE to the p2c winner and still be picked (beyond it the "
      "replica is overloaded and affinity yields to load)",
      "serve/handle.py")
_knob("serve_prefix_digest_top", int, 8,
      "top-N hottest prefix-trie roots (by reused tokens) a replica "
      "publishes in its load report for affinity routing",
      "serve/llm.py")
_knob("spec_k", int, 4,
      "draft tokens proposed per speculative-decoding round (the "
      "target verifies k+1 positions in one batched step)",
      "serve/multiplex.py")
_knob("spec_accept_floor", float, 0.2,
      "per-request acceptance-EWMA floor: a request whose draft "
      "acceptance collapses below this after the warmup rounds falls "
      "back to plain decode permanently (speculation only pays when "
      "drafts are accepted)", "serve/multiplex.py")
_knob("serve_disagg_cross_node_penalty", float, 2.0,
      "routing-score penalty for picking a decode replica on a "
      "DIFFERENT host than the chosen prefill replica (a same-host "
      "DeviceChannel KV transfer beats a cross-node store pull); 0 "
      "ignores host locality", "serve/disagg.py")
_knob("llm_stall_timeout_s", float, 120.0,
      "seconds a caller waits for the NEXT token from the LLM decode "
      "loop before declaring the stream stalled (per-request deadline_s "
      "caps it further)", "serve/llm.py")
_knob("llm_block_size", int, 16,
      "tokens per paged-KV block (prefix sharing granularity; smaller = "
      "finer reuse, more table entries)", "serve/llm.py")
_knob("llm_prefill_chunk", int, 8,
      "prompt tokens consumed per engine step during chunked prefill "
      "(1 = token-at-a-time like decode; larger drains long prompts in "
      "fewer steps without stalling in-flight decodes)", "serve/llm.py")

# -- bench / watch ----------------------------------------------------------
_knob("pool_prestart", int, 4,
      "warm pool workers kept prestarted (reference worker_pool prestart "
      "role): actor creation and task bursts claim these instead of "
      "cold-spawning", "ray_tpu/core/runtime.py")
_knob("attn_block_q", int, 512,
      "flash-attention query tile (rows per MXU block)",
      "ray_tpu/models/transformer.py")
_knob("attn_block_k", int, 512,
      "flash-attention key/value tile (cols per MXU block)",
      "ray_tpu/models/transformer.py")
_knob("xla_compiler_options", str, "",
      "space-separated k=v XLA compile options for the train step "
      "(e.g. xla_tpu_scoped_vmem_limit_kib=65536). Passed per-jit, NOT "
      "via XLA_FLAGS: TPU flags in XLA_FLAGS abort the host-side XLA "
      "parser on the tunneled axon backend",
      "ray_tpu/train/train_state.py")
_knob("bench_child_timeout", float, 420.0,
      "per-attempt timeout for the bench train-step child", "bench.py")
_knob("bench_retries", int, 3, "bench train-step attempts", "bench.py")
_knob("bench_budget", float, 700.0, "total bench wall-clock budget",
      "bench.py")
_knob("watch_interval", float, 600.0,
      "TPU tunnel probe cadence for `ray_tpu bench --watch`",
      "util/tpu_watch.py")
_knob("watch_refresh", float, 7200.0,
      "re-run the on-chip bench when the cached result is older than this",
      "util/tpu_watch.py")

# Internal coordination values (not tuning knobs, listed for completeness;
# set by the runtime itself): RTPU_WORKER (worker dial-back address),
# RTPU_CLUSTER_AUTHKEY (cluster auth secret), RTPU_COORDINATOR_HOST
# (collective rendezvous), RTPU_WATCH_LOG, RTPU_NUMERICS_SMALL,
# RTPU_EXPERIMENTAL_NOSET_TPU_VISIBLE_CHIPS (reference
# RAY_EXPERIMENTAL_NOSET_* analog).


def env_name(name: str) -> str:
    return "RTPU_" + name.upper()


def get(name: str) -> Any:
    """Resolve a knob: env override if set (parsed to the knob's type,
    falling back to the default on a parse error), else the default."""
    k = _REGISTRY[name]
    raw = os.environ.get(env_name(name))
    if raw is None:
        return k.default
    try:
        return k.type(raw)
    except (ValueError, TypeError):
        return k.default


def describe() -> List[dict]:
    """Table rows for the CLI/docs: name, env, type, default, current,
    overridden, doc."""
    rows = []
    for k in _REGISTRY.values():
        cur = get(k.name)
        rows.append({
            "name": k.name,
            "env": env_name(k.name),
            "type": getattr(k.type, "__name__", str(k.type)),
            "default": k.default,
            "current": cur,
            "overridden": cur != k.default,
            "where": k.where,
            "doc": k.doc,
        })
    return rows
