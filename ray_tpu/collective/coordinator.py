"""Coordinator actor: rendezvous + reduction meeting point for STORE groups.

Role analog: the reference's named ``Info`` store actor used for NCCL-UID
rendezvous (``nccl_collective_group.py:29`` ``Rendezvous``) — generalized
here to also perform the reductions themselves, which is what makes the
STORE backend a working gloo replacement: ranks post numpy contributions,
the last arriver reduces, everyone polls the result slot.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np


def _reduce(parts: List[np.ndarray], op: str) -> np.ndarray:
    acc = np.array(parts[0], copy=True)
    for p in parts[1:]:
        if op in ("sum", "mean"):
            acc += p
        elif op == "product":
            acc *= p
        elif op == "max":
            np.maximum(acc, p, out=acc)
        elif op == "min":
            np.minimum(acc, p, out=acc)
        else:
            raise ValueError(f"unknown reduce op {op}")
    if op == "mean":
        acc = acc / len(parts)
    return acc


class CollectiveCoordinator:
    """One instance per named group, created by whoever declares the group.

    Every op is keyed by a per-rank monotonically increasing sequence number
    (ranks must issue collectives in the same order — same contract NCCL
    imposes). Results are kept until every rank has fetched them.
    """

    def __init__(self, world_size: int):
        self.world_size = world_size
        self._pending: Dict[Tuple[str, int], Dict[int, Any]] = {}
        self._results: Dict[Tuple[str, int], Tuple[Any, set]] = {}
        self._p2p: Dict[Tuple[int, int, int], Any] = {}
        self._meta: Dict[str, Any] = {}
        self._meta_ts: Dict[str, float] = {}

    def world(self) -> int:
        return self.world_size

    # -- metadata / rendezvous ------------------------------------------------
    def set_meta(self, key: str, value: Any) -> None:
        import time

        self._meta[key] = value
        self._meta_ts[key] = time.monotonic()

    def get_meta(self, key: str) -> Any:
        return self._meta.get(key)

    def get_meta_fresh(self, key: str, max_age_s: float) -> Any:
        """Value only if set within ``max_age_s`` by THIS actor's clock —
        rendezvous readers use it to reject addresses left behind by a
        crashed previous incarnation of the group."""
        import time

        ts = self._meta_ts.get(key)
        if ts is None or time.monotonic() - ts > max_age_s:
            return None
        return self._meta.get(key)

    # -- collectives ----------------------------------------------------------
    def contribute(self, kind: str, seq: int, rank: int, part: Any,
                   op: str = "sum", root: int = 0) -> Optional[Any]:
        """Post rank's contribution; returns the result if this completes it."""
        key = (kind, seq)
        slot = self._pending.setdefault(key, {})
        slot[rank] = part
        if len(slot) < self.world_size:
            return None
        parts = [slot[r] for r in range(self.world_size)]
        del self._pending[key]
        if kind in ("allreduce", "reduce"):
            result = _reduce([np.asarray(p) for p in parts], op)
        elif kind in ("allgather", "gather"):
            result = parts
        elif kind == "broadcast":
            result = parts[root]
        elif kind == "reducescatter":
            reduced = _reduce([np.asarray(p) for p in parts], op)
            result = np.array_split(reduced, self.world_size, axis=0)
        elif kind == "alltoall":
            # parts[r] is a list of world_size chunks; rank i gets chunk i of each.
            result = [[parts[r][i] for r in range(self.world_size)]
                      for i in range(self.world_size)]
        elif kind == "barrier":
            result = True
        else:
            raise ValueError(f"unknown collective kind {kind}")
        self._results[key] = (result, set())
        return self._take(key, rank)

    def _take(self, key, rank):
        result, taken = self._results[key]
        taken.add(rank)
        kind = key[0]
        if kind in ("reducescatter", "alltoall"):
            out = result[rank]
        elif kind in ("reduce", "gather"):
            out = result  # root-only semantics enforced caller-side
        else:
            out = result
        if len(taken) >= self.world_size:
            del self._results[key]
        return out

    def fetch(self, kind: str, seq: int, rank: int) -> Tuple[bool, Any]:
        key = (kind, seq)
        if key not in self._results:
            return False, None
        return True, self._take(key, rank)

    # -- p2p ------------------------------------------------------------------
    def send(self, src: int, dst: int, seq: int, value: Any) -> None:
        self._p2p[(src, dst, seq)] = value

    def recv(self, src: int, dst: int, seq: int) -> Tuple[bool, Any]:
        key = (src, dst, seq)
        if key in self._p2p:
            return True, self._p2p.pop(key)
        return False, None
