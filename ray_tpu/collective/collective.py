"""Host-level collective API — parity with ``ray.util.collective``.

Reference surface: ``python/ray/util/collective/collective.py``
(``init_collective_group :120``, ``create_collective_group :151``,
``allreduce :258``, ``barrier :298``, ``reduce :311``, ``broadcast :373``,
``allgather :423``, ``reducescatter :472``, ``send :531``, ``recv :594``).

Two backends (see :mod:`ray_tpu.collective.types`):

- STORE — works between any processes/actors; reductions run through a
  named coordinator actor + the shared-memory object store. This is the
  gloo-analog control path.
- XLA — for jax arrays on the devices a single process owns; verbs execute
  as jitted ``shard_map`` programs over a local 1-D mesh, i.e. real ICI
  collectives. Cross-host device collectives belong inside your pjit
  program (annotate shardings; see ray_tpu.parallel) — that is the
  TPU-idiomatic path, not host-initiated verbs.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.collective.types import Backend, ReduceOp

_groups: Dict[str, "BaseGroup"] = {}
_lock = threading.Lock()

_COORD_PREFIX = "rtpu_collective_coord:"


def _routable_host() -> str:
    """An address OTHER hosts can reach (rendezvous coordinator binding).
    ``gethostbyname(gethostname())`` maps to loopback on common
    /etc/hosts layouts, which would break cross-host groups; the UDP
    connect trick reads the outbound interface without sending a packet.
    Override with RTPU_COORDINATOR_HOST."""
    import os
    import socket

    env = os.environ.get("RTPU_COORDINATOR_HOST")
    if env:
        return env
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("8.8.8.8", 80))
        return s.getsockname()[0]
    except Exception:
        return socket.gethostbyname(socket.gethostname())
    finally:
        s.close()


def _get_or_create_coordinator(group_name: str, world_size: int):
    """Get or create the named coordinator actor. Returns (handle, created)."""
    import ray_tpu
    from ray_tpu.collective.coordinator import CollectiveCoordinator

    name = _COORD_PREFIX + group_name
    try:
        return ray_tpu.get_actor(name), False
    except ValueError:
        try:
            handle = (
                ray_tpu.remote(CollectiveCoordinator)
                .options(name=name, max_concurrency=max(4, world_size))
                .remote(world_size)
            )
            return handle, True
        except Exception:
            return ray_tpu.get_actor(name), False


class BaseGroup:
    def __init__(self, world_size: int, rank: int, group_name: str):
        self.world_size = world_size
        self.rank = rank
        self.group_name = group_name

    def destroy(self):
        pass


class StoreGroup(BaseGroup):
    """Collectives through the coordinator actor (CPU / control plane)."""

    def __init__(self, world_size: int, rank: int, group_name: str):
        super().__init__(world_size, rank, group_name)
        self._coord, self._created_coord = _get_or_create_coordinator(
            group_name, world_size)
        self._seq: Dict[str, int] = {}
        self._p2p_seq: Dict[tuple, int] = {}

    def destroy(self):
        if self._created_coord:
            import ray_tpu
            try:
                ray_tpu.kill(self._coord)
            except Exception:
                pass

    def _run(self, kind: str, part: Any, op: str = "sum", root: int = 0,
             timeout_s: float = 60.0):
        import ray_tpu

        # Commit the sequence number only on success so a timed-out op can be
        # retried with the same seq (the late contribution still pairs up).
        seq = self._seq.get(kind, 0)
        out = ray_tpu.get(
            self._coord.contribute.remote(kind, seq, self.rank, part, op, root)
        )
        if out is not None:
            self._seq[kind] = seq + 1
            return out
        deadline = time.monotonic() + timeout_s
        delay = 0.0005
        while time.monotonic() < deadline:
            done, res = ray_tpu.get(self._coord.fetch.remote(kind, seq, self.rank))
            if done:
                self._seq[kind] = seq + 1
                return res
            time.sleep(delay)
            delay = min(delay * 2, 0.05)
        raise TimeoutError(
            f"collective {kind}#{seq} timed out in group {self.group_name} "
            f"(rank {self.rank}/{self.world_size})"
        )

    def allreduce(self, tensor, op: ReduceOp = ReduceOp.SUM):
        return self._run("allreduce", np.asarray(tensor), op=op.value)

    def barrier(self):
        self._run("barrier", None)

    def reduce(self, tensor, root_rank: int = 0, op: ReduceOp = ReduceOp.SUM):
        out = self._run("reduce", np.asarray(tensor), op=op.value, root=root_rank)
        return out if self.rank == root_rank else np.asarray(tensor)

    def broadcast(self, tensor, root_rank: int = 0):
        return self._run("broadcast", np.asarray(tensor), root=root_rank)

    def allgather(self, tensor):
        return self._run("allgather", np.asarray(tensor))

    def reducescatter(self, tensor, op: ReduceOp = ReduceOp.SUM):
        return self._run("reducescatter", np.asarray(tensor), op=op.value)

    def alltoall(self, chunks: List[Any]):
        if len(chunks) != self.world_size:
            raise ValueError("alltoall needs world_size chunks")
        return self._run("alltoall", [np.asarray(c) for c in chunks])

    def send(self, tensor, dst_rank: int):
        import ray_tpu

        key = (self.rank, dst_rank)
        seq = self._p2p_seq.get(key, 0)
        ray_tpu.get(self._coord.send.remote(self.rank, dst_rank, seq, np.asarray(tensor)))
        self._p2p_seq[key] = seq + 1

    def recv(self, src_rank: int, timeout_s: float = 60.0):
        import ray_tpu

        key = (src_rank, self.rank)
        seq = self._p2p_seq.get(key, 0)
        deadline = time.monotonic() + timeout_s
        delay = 0.0005
        while time.monotonic() < deadline:
            done, val = ray_tpu.get(self._coord.recv.remote(src_rank, self.rank, seq))
            if done:
                self._p2p_seq[key] = seq + 1
                return val
            time.sleep(delay)
            delay = min(delay * 2, 0.05)
        raise TimeoutError(f"recv from rank {src_rank} timed out")


class XlaGroup(BaseGroup):
    """Device collectives over this process's local chips (1-D mesh).

    world_size here is the local device count; ``tensors`` arguments are
    per-device lists (the reference's ``*_multigpu`` variants,
    ``collective.py:340`` etc.) or a single sharded jax.Array.
    """

    def __init__(self, world_size: int, rank: int, group_name: str):
        super().__init__(world_size, rank, group_name)
        import jax
        from jax.sharding import Mesh

        devs = jax.local_devices()
        if world_size > len(devs):
            raise ValueError(
                f"XLA group world_size {world_size} > local devices {len(devs)}"
            )
        arr = np.asarray(devs[:world_size], dtype=object)
        self.mesh = Mesh(arr, axis_names=("x",))
        self._cache: Dict[tuple, Any] = {}

    def _sharded(self, tensors: List[Any]):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        arrs = [np.asarray(t) for t in tensors]
        stacked = np.stack(arrs, axis=0)
        sharding = NamedSharding(self.mesh, P("x"))
        return jax.device_put(stacked, sharding)

    def _collective(self, kind: str, op: str = "sum", root: int = 0,
                    perm: tuple = ()):
        key = (kind, op, root, perm)
        if key in self._cache:
            return self._cache[key]
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P
        from jax import lax, shard_map

        def red_fn(x):
            if op == "product":
                g = lax.all_gather(x, "x", axis=0)
                return jnp.prod(g, axis=0)
            red = {"sum": lax.psum, "mean": lax.pmean, "max": lax.pmax,
                   "min": lax.pmin}[op]
            return red(x, "x")

        def body(x):
            x = x[0]  # drop the leading per-device dim of this shard
            if kind == "allreduce":
                return red_fn(x)[None]
            if kind == "allgather":
                return lax.all_gather(x, "x", axis=0, tiled=True)[None]
            if kind == "reducescatter":
                return lax.psum_scatter(x, "x", scatter_dimension=0, tiled=True)[None]
            if kind == "reduce":
                # only the root keeps the reduction; others keep their input
                # (reference collective.py:311 semantics)
                i = lax.axis_index("x")
                return jnp.where(i == root, red_fn(x), x)[None]
            if kind == "broadcast":
                # root's tensor everywhere (reference collective.py:373)
                g = lax.all_gather(x, "x", axis=0)
                return g[root][None]
            if kind == "permute":
                # device-to-device send/recv: (src, dst) pairs become one
                # ppermute — the SPMD-native form of the reference's
                # send/recv_multigpu (collective.py:531/594); devices not
                # named as a destination keep their input
                shifted = lax.ppermute(x, "x", perm=list(perm))
                i = lax.axis_index("x")
                is_dst = jnp.zeros((), bool)
                for _, dst in perm:
                    is_dst = jnp.logical_or(is_dst, i == dst)
                return jnp.where(is_dst, shifted, x)[None]
            if kind == "alltoall":
                # x: [world, chunk...] per device -> transpose chunk i to
                # device i (lax.all_to_all over ICI)
                return lax.all_to_all(x, "x", split_axis=0, concat_axis=0,
                                      tiled=False)[None]
            raise ValueError(kind)

        fn = jax.jit(shard_map(body, mesh=self.mesh, in_specs=P("x"),
                               out_specs=P("x"), check_vma=False))
        self._cache[key] = fn
        return fn

    def _per_device(self, out):
        return [np.asarray(s.data)[0] for s in out.addressable_shards]

    def allreduce(self, tensors: List[Any], op: ReduceOp = ReduceOp.SUM):
        out = self._collective("allreduce", op.value)(self._sharded(tensors))
        return self._per_device(out)

    def allgather(self, tensors: List[Any]):
        out = self._collective("allgather")(self._sharded(tensors))
        return self._per_device(out)

    def reducescatter(self, tensors: List[Any], op: ReduceOp = ReduceOp.SUM):
        out = self._collective("reducescatter", op.value)(self._sharded(tensors))
        return self._per_device(out)

    def reduce(self, tensors: List[Any], root_rank: int = 0,
               op: ReduceOp = ReduceOp.SUM):
        out = self._collective("reduce", op.value, root=root_rank)(
            self._sharded(tensors))
        return self._per_device(out)

    def broadcast(self, tensors: List[Any], root_rank: int = 0):
        out = self._collective("broadcast", root=root_rank)(
            self._sharded(tensors))
        return self._per_device(out)

    def permute(self, tensors: List[Any], pairs: List[tuple]):
        """Device-level send/recv: each (src, dst) pair ships src's tensor
        to dst in ONE ppermute over ICI."""
        out = self._collective("permute", perm=tuple(
            (int(s), int(d)) for s, d in pairs))(self._sharded(tensors))
        return self._per_device(out)

    def send(self, tensors: List[Any], dst_rank: int, src_rank: int = 0):
        """Reference send_multigpu analog: src device's tensor lands on
        dst; returns the updated per-device list."""
        return self.permute(tensors, [(src_rank, dst_rank)])

    def alltoall(self, chunk_lists: List[Any]):
        """``chunk_lists[i]`` = device i's world_size chunks; returns per-
        device transposed chunk lists (device i gets everyone's chunk i)."""
        stacked = [np.stack([np.asarray(c) for c in chunks], axis=0)
                   for chunks in chunk_lists]
        out = self._collective("alltoall")(self._sharded(stacked))
        return [list(np.asarray(s.data)[0]) for s in out.addressable_shards]

    def barrier(self):
        self.allreduce([np.zeros((8, 128), np.float32)
                        for _ in range(len(self.mesh.devices.flat))])


class XlaDistributedGroup(XlaGroup):
    """XLA collectives ACROSS MEMBER PROCESSES over one global mesh.

    Role analog: the reference NCCLGroup
    (``collective_group/nccl_collective_group.py:128``): the named
    coordinator actor fills the NCCL-unique-id rendezvous role (it carries
    the jax coordinator address), the communicator state is
    ``jax.distributed``, and every verb compiles to an XLA collective
    executed collectively by all member processes — gloo across CPU hosts,
    ICI/DCN on TPU. ``world_size``/``rank`` are PROCESS world/rank (one
    actor per process, the reference model); tensor arguments stay
    per-LOCAL-device lists like :class:`XlaGroup`.

    All members must call each verb in the same order (the NCCL contract);
    each call is one jitted ``shard_map`` program over the global mesh.
    """

    def __init__(self, world_size: int, rank: int, group_name: str):
        BaseGroup.__init__(self, world_size, rank, group_name)
        import jax
        from jax.sharding import Mesh

        self._ensure_distributed(jax)
        if jax.process_count() != world_size:
            raise ValueError(
                f"jax.distributed world has {jax.process_count()} processes;"
                f" group declared {world_size}")
        devs = np.asarray(jax.devices(), dtype=object)  # every process's
        self.mesh = Mesh(devs, axis_names=("x",))
        self._cache: Dict[tuple, Any] = {}

    def _ensure_distributed(self, jax) -> None:
        """Join the group's jax.distributed world (idempotent: a process
        already in one — e.g. a Train worker — reuses it)."""
        from jax._src import distributed as _dist

        if getattr(_dist.global_state, "client", None) is not None:
            return
        # cross-process collectives on the CPU backend ride gloo; set
        # unconditionally (no-op for TPU) — probing the backend here would
        # initialize XLA and break jax.distributed.initialize
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:
            pass
        import ray_tpu

        coord, _ = _get_or_create_coordinator(self.group_name,
                                              self.world_size)
        key = "jax_coordinator"
        if self.rank == 0:
            from ray_tpu.cluster.rpc import free_port

            addr = f"{_routable_host()}:{free_port()}"
            ray_tpu.get(coord.set_meta.remote(key, addr))
        else:
            # freshness gate (coordinator's OWN clock, no cross-host
            # skew): a stale address left by a crashed previous
            # incarnation of this group must not be trusted
            addr = None
            deadline = time.monotonic() + 120
            while addr is None and time.monotonic() < deadline:
                addr = ray_tpu.get(
                    coord.get_meta_fresh.remote(key, 120.0))
                if addr is None:
                    time.sleep(0.2)
            if addr is None:
                raise TimeoutError(
                    "rank 0 never published the jax coordinator address")
        jax.distributed.initialize(coordinator_address=addr,
                                   num_processes=self.world_size,
                                   process_id=self.rank)

    def destroy(self):
        # clear the rendezvous address so a future incarnation of this
        # group name cannot latch onto a dead coordinator
        try:
            import ray_tpu

            coord, _ = _get_or_create_coordinator(self.group_name,
                                                  self.world_size)
            ray_tpu.get(coord.set_meta.remote("jax_coordinator", None))
        except Exception:
            pass

    def _sharded(self, tensors: List[Any]):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        local = np.stack([np.asarray(t) for t in tensors], axis=0)
        sharding = NamedSharding(self.mesh, P("x"))
        return jax.make_array_from_process_local_data(sharding, local)

    def barrier(self):
        import jax

        self.allreduce([np.zeros((8, 128), np.float32)
                        for _ in range(jax.local_device_count())])


def init_collective_group(world_size: int, rank: int,
                          backend=Backend.STORE,
                          group_name: str = "default") -> BaseGroup:
    """Declare membership of this process/actor in a named group."""
    backend = Backend.parse(backend)
    with _lock:
        if group_name in _groups:
            raise RuntimeError(f"collective group {group_name!r} already initialized")
        if backend == Backend.STORE:
            g = StoreGroup(world_size, rank, group_name)
        elif backend == Backend.XLA_DISTRIBUTED:
            g = XlaDistributedGroup(world_size, rank, group_name)
        else:
            g = XlaGroup(world_size, rank, group_name)
        _groups[group_name] = g
        return g


def create_collective_group(actors, world_size: int, ranks: List[int],
                            backend=Backend.STORE,
                            group_name: str = "default"):
    """Driver-side declarative setup (reference ``collective.py:151``).

    Pre-creates the named coordinator so member actors can lazily
    ``init_collective_group`` on first verb without racing on actor
    creation (the reference spawns a named ``Info`` store actor the same
    way). ``actors``/``ranks`` are accepted for API parity; membership is
    claimed by each actor's own init call.
    """
    import ray_tpu

    Backend.parse(backend)
    coord, created = _get_or_create_coordinator(group_name, world_size)
    if created:
        ray_tpu.get(coord.world.remote())  # barrier on creation


def is_group_initialized(group_name: str = "default") -> bool:
    return group_name in _groups


def get_collective_group(group_name: str = "default") -> BaseGroup:
    if group_name not in _groups:
        raise RuntimeError(
            f"collective group {group_name!r} is not initialized in this process"
        )
    return _groups[group_name]


def destroy_collective_group(group_name: str = "default") -> None:
    with _lock:
        g = _groups.pop(group_name, None)
        if g:
            g.destroy()


def get_rank(group_name: str = "default") -> int:
    return get_collective_group(group_name).rank


def get_collective_group_size(group_name: str = "default") -> int:
    return get_collective_group(group_name).world_size


# module-level verbs (reference API shape)

def allreduce(tensor, group_name: str = "default", op: ReduceOp = ReduceOp.SUM):
    return get_collective_group(group_name).allreduce(tensor, op)


def barrier(group_name: str = "default"):
    get_collective_group(group_name).barrier()


def reduce(tensor, dst_rank: int = 0, group_name: str = "default",
           op: ReduceOp = ReduceOp.SUM):
    return get_collective_group(group_name).reduce(tensor, dst_rank, op)


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    return get_collective_group(group_name).broadcast(tensor, src_rank)


def allgather(tensor, group_name: str = "default"):
    return get_collective_group(group_name).allgather(tensor)


def reducescatter(tensor, group_name: str = "default",
                  op: ReduceOp = ReduceOp.SUM):
    return get_collective_group(group_name).reducescatter(tensor, op)


def alltoall(chunks, group_name: str = "default"):
    return get_collective_group(group_name).alltoall(chunks)


def send(tensor, dst_rank: int, group_name: str = "default"):
    get_collective_group(group_name).send(tensor, dst_rank)


def recv(src_rank: int, group_name: str = "default"):
    return get_collective_group(group_name).recv(src_rank)
