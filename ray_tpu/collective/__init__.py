"""Host-level collective communication (``ray.util.collective`` parity)."""

from ray_tpu.collective.types import (
    Backend,
    ReduceOp,
)
from ray_tpu.collective.collective import (
    init_collective_group,
    create_collective_group,
    destroy_collective_group,
    is_group_initialized,
    get_collective_group,
    get_rank,
    get_collective_group_size,
    allreduce,
    allgather,
    alltoall,
    barrier,
    broadcast,
    reduce,
    reducescatter,
    send,
    recv,
)

__all__ = [
    "Backend", "ReduceOp",
    "init_collective_group", "create_collective_group",
    "destroy_collective_group", "is_group_initialized",
    "get_collective_group", "get_rank", "get_collective_group_size",
    "allreduce", "allgather", "alltoall", "barrier", "broadcast",
    "reduce", "reducescatter", "send", "recv",
]
