"""Collective types — parity with ``python/ray/util/collective/types.py``.

Backends: the reference offers NCCL (GPU) and GLOO (CPU). Here the device
backend is XLA (collectives lower to ``jax.lax`` ops over ICI inside jitted
programs, see :mod:`ray_tpu.parallel.ops`) and the CPU/control backend is
STORE (reductions through the shared-memory object store via a coordinator
actor — the gloo-analog that works anywhere, used for rendezvous, metrics,
and small-tensor sync).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass


class Backend(str, enum.Enum):
    XLA = "xla"
    STORE = "store"
    # one group spanning MULTIPLE member processes, backed by
    # jax.distributed (the reference NCCLGroup's role)
    XLA_DISTRIBUTED = "xla_distributed"

    @classmethod
    def parse(cls, value) -> "Backend":
        if isinstance(value, Backend):
            return value
        v = str(value).lower()
        if v in ("xla", "tpu", "ici"):
            return cls.XLA
        if v in ("xla_distributed", "jax_distributed", "distributed",
                 "multiprocess"):
            return cls.XLA_DISTRIBUTED
        if v in ("store", "cpu", "gloo"):
            return cls.STORE
        if v in ("nccl", "mpi"):
            raise ValueError(
                f"backend {value!r} is GPU/MPI-specific; use 'xla' (device) "
                f"or 'store' (cpu) in ray_tpu"
            )
        raise ValueError(f"unknown collective backend: {value!r}")


class ReduceOp(str, enum.Enum):
    SUM = "sum"
    PRODUCT = "product"
    MAX = "max"
    MIN = "min"
    MEAN = "mean"


@dataclass
class AllReduceOptions:
    reduceOp: ReduceOp = ReduceOp.SUM
    timeout_ms: int = 30000


@dataclass
class BarrierOptions:
    timeout_ms: int = 30000


@dataclass
class ReduceOptions:
    reduceOp: ReduceOp = ReduceOp.SUM
    root_rank: int = 0
    timeout_ms: int = 30000


@dataclass
class BroadcastOptions:
    root_rank: int = 0
    timeout_ms: int = 30000


@dataclass
class AllGatherOptions:
    timeout_ms: int = 30000


@dataclass
class ReduceScatterOptions:
    reduceOp: ReduceOp = ReduceOp.SUM
    timeout_ms: int = 30000


@dataclass
class SendOptions:
    dst_rank: int = 0
    timeout_ms: int = 30000


@dataclass
class RecvOptions:
    src_rank: int = 0
    timeout_ms: int = 30000
