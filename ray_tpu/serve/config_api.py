"""Declarative serve config: YAML/JSON schema + deploy + REST surface.

Role analog: the reference's serve config pipeline — ``serve build`` /
``serve deploy`` CLI (``python/ray/serve/scripts.py``), the pydantic
config schema (``serve/schema.py``), and the dashboard serve REST API
(``dashboard/modules/serve``). Schema (YAML or JSON)::

    applications:
      - name: default            # optional (default "default")
        import_path: mypkg.app:app   # module:attr -> Application/Deployment
        route_prefix: /app           # optional (default = deployment name)
        deployments:                 # optional per-deployment overrides
          - name: Model
            num_replicas: 2
            max_ongoing_requests: 8

``deploy_config`` applies it against the in-process serve instance; the
dashboard exposes GET/PUT ``/api/serve/applications`` so a remote
``ray_tpu serve deploy/status`` works against a live cluster head.
"""

from __future__ import annotations

import importlib
from typing import Any, Dict, List, Optional


def load_config(path: str) -> Dict[str, Any]:
    import yaml

    with open(path) as f:
        cfg = yaml.safe_load(f)
    if not isinstance(cfg, dict) or "applications" not in cfg:
        raise ValueError(
            "serve config must be a mapping with an 'applications' list")
    return cfg


def import_attr(import_path: str):
    """``module.sub:attr`` -> the attribute (reference import_attr role)."""
    if ":" not in import_path:
        raise ValueError(
            f"import_path {import_path!r} must look like 'module:attr'")
    mod_name, _, attr = import_path.partition(":")
    mod = importlib.import_module(mod_name)
    obj = mod
    for part in attr.split("."):
        obj = getattr(obj, part)
    return obj


def deploy_config(cfg: Dict[str, Any]) -> List[str]:
    """Deploy every application in ``cfg`` in-process; returns app names."""
    from ray_tpu import serve
    from ray_tpu.serve.deployment import Application, Deployment

    deployed = []
    for app_cfg in cfg.get("applications", []):
        app = import_attr(app_cfg["import_path"])
        if isinstance(app, Deployment):
            app = app.bind()
        if not isinstance(app, Application):
            raise TypeError(
                f"{app_cfg['import_path']} resolved to {type(app).__name__};"
                " expected an Application or Deployment")
        overrides = {d["name"]: {k: v for k, v in d.items() if k != "name"}
                     for d in app_cfg.get("deployments", [])}
        if overrides:
            for node in app.flatten().values():
                dep = node.deployment
                opts = overrides.get(dep.name)
                if opts:
                    node.deployment = dep.options(**opts)
        name = app_cfg.get("name", "default")
        serve.run(app, name=name,
                  route_prefix=app_cfg.get("route_prefix"))
        deployed.append(name)
    return deployed


def serve_rest_get() -> Dict[str, Any]:
    """GET /api/serve/applications payload."""
    from ray_tpu import serve

    try:
        return {"applications": serve.status()}
    except Exception as e:
        return {"applications": {}, "error": str(e)}


def serve_models_get() -> Dict[str, Any]:
    """GET /api/models payload: per-deployment replica model residency
    (tier, swap counters, inflight) plus prefix-digest summaries."""
    from ray_tpu.serve import api as serve_api

    try:
        return {"deployments": serve_api.model_report()}
    except Exception as e:
        return {"deployments": {}, "error": str(e)}


def serve_rest_put(cfg: Dict[str, Any]) -> Dict[str, Any]:
    """PUT /api/serve/applications: declarative (re)deploy."""
    return {"deployed": deploy_config(cfg)}


def serve_rest_delete() -> Dict[str, Any]:
    from ray_tpu import serve

    serve.shutdown()
    return {"shutdown": True}
