"""Paged KV-cache bookkeeping: refcounted block pool + prefix trie.

The serving tier's memory manager (vLLM's PagedAttention block manager
role, arxiv 2309.06180; the Gemma-on-TPU serving comparison in PAPERS.md
shows paged KV + batching policy — not raw FLOPs — decide TPU serving
throughput). Physical KV storage is a device array of fixed-size token
blocks (``models.init_cache_paged``); THIS module is the host-side truth
about who owns which block:

- :class:`BlockPool` — a refcounted free-list over the physical blocks.
  Admission claims blocks, not slots; a request holds one reference per
  table entry, the prefix cache holds one per trie node, and a block
  returns to the free list only when the last reference drops — which is
  exactly the leak-detection surface the chaos tests assert on (free
  count returns to baseline after a replica death).
- :class:`PrefixCache` — a hash trie keyed by FULL blocks of prompt
  tokens. Two requests whose prompts share a system prefix map the
  shared tokens to the SAME immutable physical blocks; only full blocks
  are ever shared, so shared blocks are never written (a capped match
  that reuses a partial tail block goes through copy-on-write instead —
  the pool's :meth:`BlockPool.need_cow` + the engine's device-side
  ``models.copy_kv_block``). Eviction is LRU over leaves whose only
  remaining reference is the trie's own.

Pure host-side data structures (no jax, no device state): unit-testable
without a mesh, and the engine stays the single owner of device arrays.
"""

from __future__ import annotations

import hashlib
import time
from typing import Dict, List, Optional, Sequence, Tuple


def prefix_key_digest(tokens: Sequence[int]) -> str:
    """Stable cross-process digest of one block's token tuple — the key
    replicas publish in their prefix digest and handles recompute from a
    request's first prompt block to route for affinity. Content-hashed
    (not id-based) so two replicas that independently cached the same
    system prompt advertise the SAME key."""
    raw = ",".join(str(int(t)) for t in tokens).encode()
    return hashlib.blake2b(raw, digest_size=8).hexdigest()


class KVCacheError(RuntimeError):
    """Invariant violation in block accounting (double free, foreign
    block) — always a bug, never load-dependent."""


class BlockPool:
    """Refcounted pool of physical KV block ids ``0..num_blocks-1``.

    ``alloc`` is all-or-nothing (admission must never half-claim), and
    every block's lifecycle is ref-based: allocation returns blocks at
    refcount 1; ``retain``/``release`` move them; refcount 0 returns the
    block to the free list. LIFO reuse keeps recently-touched HBM warm.
    """

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 1 or block_size < 1:
            raise ValueError(
                f"need >=1 block of >=1 tokens, got {num_blocks}x{block_size}")
        self.num_blocks = num_blocks
        self.block_size = block_size
        self._free: List[int] = list(range(num_blocks - 1, -1, -1))
        self._ref: List[int] = [0] * num_blocks

    # -- views -------------------------------------------------------------

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def used_count(self) -> int:
        return self.num_blocks - len(self._free)

    def refcount(self, block_id: int) -> int:
        return self._ref[block_id]

    def blocks_for_tokens(self, n_tokens: int) -> int:
        """Table length covering ``n_tokens`` positions."""
        return -(-max(n_tokens, 0) // self.block_size)

    # -- lifecycle ---------------------------------------------------------

    def alloc(self, n: int) -> Optional[List[int]]:
        """Claim ``n`` blocks at refcount 1, or None (nothing claimed) if
        fewer than ``n`` are free — admission decides queue vs shed."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            return None
        out = [self._free.pop() for _ in range(n)]
        for b in out:
            self._ref[b] = 1
        return out

    def retain(self, block_id: int) -> None:
        if self._ref[block_id] <= 0:
            raise KVCacheError(f"retain of free block {block_id}")
        self._ref[block_id] += 1

    def release(self, block_id: int) -> bool:
        """Drop one reference; True when the block returned to the free
        list (the caller held the last reference)."""
        r = self._ref[block_id]
        if r <= 0:
            raise KVCacheError(f"release of free block {block_id}")
        self._ref[block_id] = r - 1
        if r == 1:
            self._free.append(block_id)
            return True
        return False

    def release_all(self, block_ids: Sequence[int]) -> int:
        return sum(1 for b in block_ids if self.release(b))

    def need_cow(self, block_id: int) -> bool:
        """True when writing into ``block_id`` requires copy-on-write:
        someone else (another request or the prefix trie) also holds it."""
        return self._ref[block_id] > 1


class _TrieNode:
    __slots__ = ("key", "block_id", "children", "parent", "last_used",
                 "hit_weight")

    def __init__(self, key: Optional[Tuple[int, ...]],
                 block_id: Optional[int], parent: Optional["_TrieNode"]):
        self.key = key
        self.block_id = block_id
        self.parent = parent
        self.children: Dict[Tuple[int, ...], "_TrieNode"] = {}
        self.last_used = 0.0
        # tokens reused through this ROOT child (only root children
        # accumulate weight — the digest ranks system prompts, and a
        # system prompt is identified by its first block)
        self.hit_weight = 0


class PrefixCache:
    """Hash trie mapping chains of FULL token blocks to physical blocks.

    ``match`` walks the prompt block-by-block and retains every matched
    block on behalf of the caller (the request's table references); the
    match is capped at ``len(tokens) - 1`` so at least one prompt token
    always runs through the model — its logits seed sampling. When the
    cap lands mid-block the tail block is returned as a copy-on-write
    source, never as a table entry.

    ``insert`` registers a finished request's full prompt blocks;
    existing chains are adopted as-is (no duplicate physical blocks for
    one prefix). ``evict`` reclaims LRU leaves whose only reference is
    the trie's.
    """

    def __init__(self, pool: BlockPool):
        self.pool = pool
        self.block_size = pool.block_size
        self._root = _TrieNode(None, None, None)
        self._nodes = 0
        # lookup-level counters (the engine mirrors them into metrics)
        self.hits = 0
        self.misses = 0
        self.hit_tokens = 0
        self.evictions = 0

    def __len__(self) -> int:
        return self._nodes

    def _chunks(self, tokens: Sequence[int]) -> List[Tuple[int, ...]]:
        bs = self.block_size
        n_full = len(tokens) // bs
        return [tuple(tokens[i * bs:(i + 1) * bs]) for i in range(n_full)]

    # -- lookup ------------------------------------------------------------

    def match(self, tokens: Sequence[int]
              ) -> Tuple[List[int], int, Optional[int]]:
        """Longest shared prefix of ``tokens`` already cached.

        Returns ``(full_blocks, matched_tokens, cow_src)``:
        ``full_blocks`` are retained for the caller and usable as-is;
        ``matched_tokens`` counts reused positions (capped at
        ``len(tokens) - 1``); ``cow_src`` is a block id (also retained)
        whose first ``matched_tokens % block_size`` positions must be
        COPIED into a fresh block when the cap split a block — the caller
        releases it after the device copy.
        """
        node = self._root
        chain: List[_TrieNode] = []
        now = time.monotonic()
        for key in self._chunks(tokens):
            child = node.children.get(key)
            if child is None:
                break
            child.last_used = now
            chain.append(child)
            node = child
        if not chain:
            self.misses += 1
            return [], 0, None
        first_child = chain[0]
        matched = len(chain) * self.block_size
        cow_src: Optional[int] = None
        if matched >= len(tokens):
            # cap below the full prompt: the final matched block is only
            # partially reused -> copy-on-write source, not a table entry
            matched = len(tokens) - 1
            tail = chain.pop()
            if matched % self.block_size:
                cow_src = tail.block_id
                self.pool.retain(cow_src)
        blocks = [n.block_id for n in chain]
        for b in blocks:
            self.pool.retain(b)
        self.hits += 1
        self.hit_tokens += matched
        first_child.hit_weight += matched
        return blocks, matched, cow_src

    # -- registration ------------------------------------------------------

    def insert(self, tokens: Sequence[int], block_ids: Sequence[int]) -> int:
        """Register a request's prompt: ``block_ids[i]`` holds tokens
        ``[i*bs, (i+1)*bs)``. Only full blocks are inserted; new nodes
        retain their block for the trie, existing nodes keep theirs (the
        request's duplicate block simply gets released by its owner).
        Returns how many NEW blocks the trie adopted."""
        node = self._root
        adopted = 0
        now = time.monotonic()
        for i, key in enumerate(self._chunks(tokens)):
            if i >= len(block_ids):
                break
            child = node.children.get(key)
            if child is None:
                child = _TrieNode(key, block_ids[i], node)
                self.pool.retain(block_ids[i])
                node.children[key] = child
                self._nodes += 1
                adopted += 1
            child.last_used = now
            node = child
        return adopted

    # -- eviction ----------------------------------------------------------

    def _leaves(self) -> List[_TrieNode]:
        out: List[_TrieNode] = []
        stack = [self._root]
        while stack:
            n = stack.pop()
            kids = list(n.children.values())
            if not kids and n is not self._root:
                out.append(n)
            stack.extend(kids)
        return out

    def evict(self, n_blocks: int) -> int:
        """Reclaim up to ``n_blocks`` physical blocks by dropping LRU
        leaves whose ONLY reference is the trie's (a leaf a live request
        still shares is pinned). Dropping a leaf may expose its parent;
        the scan repeats until satisfied or nothing is reclaimable."""
        reclaimed = 0
        while reclaimed < n_blocks:
            victims = [l for l in self._leaves()
                       if self.pool.refcount(l.block_id) == 1]
            if not victims:
                break
            victims.sort(key=lambda l: l.last_used)
            for leaf in victims:
                leaf.parent.children.pop(leaf.key, None)
                self._nodes -= 1
                if self.pool.release(leaf.block_id):
                    reclaimed += 1
                    self.evictions += 1
                if reclaimed >= n_blocks:
                    break
        return reclaimed

    def clear(self) -> int:
        """Drop every node (engine shutdown); returns blocks freed."""
        freed = 0
        stack = list(self._root.children.values())
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            if self.pool.release(n.block_id):
                freed += 1
        self._root.children.clear()
        self._nodes = 0
        return freed

    def evictable_count(self) -> int:
        """Blocks reclaimable by :meth:`evict` RIGHT NOW: nodes whose
        whole subtree is only trie-referenced (eviction is leaf-first,
        so a node above a request-pinned block is stuck until the sharer
        releases). This is the capacity signal routing/autoscaling must
        add to the free count — a warm idle replica's pool reads ~full
        otherwise, which would steer traffic to cold replicas and drive
        autoscale runaway."""

        # iterative post-order (chains are one node per prompt block —
        # recursion would blow the stack on long-context configs):
        # a node is counted when its whole subtree is trie-only
        count = 0
        stack = [(n, False) for n in self._root.children.values()]
        free: Dict[int, bool] = {}          # id(node) -> subtree free?
        while stack:
            n, visited = stack.pop()
            if not visited:
                stack.append((n, True))
                stack.extend((c, False) for c in n.children.values())
                continue
            ok = (self.pool.refcount(n.block_id) == 1
                  and all(free[id(c)] for c in n.children.values()))
            free[id(n)] = ok
            if ok:
                count += 1
        return count

    def digest(self, top: int = 8) -> List[Tuple[str, int]]:
        """Top trie roots by hit-weight as ``(key_digest, weight)`` pairs
        — the cluster-wide prefix-affinity signal. One entry per resident
        ROOT child (≈ one per distinct system prompt). Roots that never
        produced a hit publish weight 0: a HELD root is routable — the
        tenant's first repeat request would hit it, so omitting cold
        entries scatters every session's opening requests across the
        fleet before affinity can converge. Hot roots sort first so the
        ``top`` cap sheds cold ones under pressure. Small and stable by
        construction: ``top`` entries of ~24 bytes ride every load
        report."""
        roots = sorted(self._root.children.values(),
                       key=lambda n: -n.hit_weight)
        return [(prefix_key_digest(n.key), n.hit_weight)
                for n in roots[:max(top, 0)]]

    def stats(self) -> Dict[str, int]:
        return {"nodes": self._nodes, "hits": self.hits,
                "misses": self.misses, "hit_tokens": self.hit_tokens,
                "evictions": self.evictions}
