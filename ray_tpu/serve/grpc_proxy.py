"""gRPC ingress: the second data-plane protocol next to HTTP.

Role analog: ``python/ray/serve/_private/proxy.py:534`` (``gRPCProxy``) +
the reference's ``serve.proto`` service. Implementation differs: instead
of protoc-generated stubs, one generic service with JSON-bytes payloads —
callable from any gRPC client without codegen::

    ch = grpc.insecure_channel(addr)
    predict = ch.unary_unary("/ray_tpu.serve.ServeAPI/Predict")
    resp = json.loads(predict(json.dumps(
        {"deployment": "echo", "arg": {"x": 1}}).encode()))

Methods (all payloads are UTF-8 JSON bytes):

- ``Predict``        unary-unary  {"deployment", "arg"?} -> {"result"}
- ``PredictStream``  unary-stream same request, one {"result"} per yield
- ``Healthz``        unary-unary  {} -> {"status": "ok"}
- ``ListDeployments`` unary-unary {} -> {"deployments": [...]}

Routing table and handle semantics are shared with ``HTTPProxy``: both
ingresses front the same ``DeploymentHandle`` router (pow-2 replica
choice, streaming, multiplex).
"""

from __future__ import annotations

import json
from typing import Any, Dict, Optional

from ray_tpu.serve.handle import DeploymentHandle

SERVICE = "ray_tpu.serve.ServeAPI"


def _ident(b):
    return b


class GrpcProxy:
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 max_workers: int = 16):
        self.host = host
        self.port = port
        self.max_workers = max_workers
        self._handles: Dict[str, DeploymentHandle] = {}
        self._server = None

    def register(self, route: str, handle: DeploymentHandle) -> None:
        self._handles[route.strip("/")] = handle

    # -- handlers ---------------------------------------------------------

    def _parse(self, request: bytes, context):
        import grpc

        try:
            req = json.loads(request or b"{}")
        except json.JSONDecodeError:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                          "request payload is not JSON")
        return req

    def _resolve(self, req: dict, context) -> DeploymentHandle:
        import grpc

        name = str(req.get("deployment") or "").strip("/")
        handle = self._handles.get(name)
        if handle is None:
            context.abort(grpc.StatusCode.NOT_FOUND,
                          f"no deployment {name!r}")
        return handle

    def _predict(self, request: bytes, context) -> bytes:
        import grpc

        req = self._parse(request, context)
        handle = self._resolve(req, context)
        arg: Any = req.get("arg")
        try:
            resp = handle.remote(arg) if arg is not None else handle.remote()
            return json.dumps({"result": resp.result()}).encode()
        except Exception as e:  # noqa: BLE001
            context.abort(grpc.StatusCode.INTERNAL, str(e))

    def _predict_stream(self, request: bytes, context):
        import grpc

        req = self._parse(request, context)
        handle = self._resolve(req, context)
        arg: Any = req.get("arg")
        try:
            gen = (handle.options(stream=True).remote(arg)
                   if arg is not None
                   else handle.options(stream=True).remote())
            for item in gen:
                yield json.dumps({"result": item}).encode()
        except Exception as e:  # noqa: BLE001
            context.abort(grpc.StatusCode.INTERNAL, str(e))

    def _healthz(self, request: bytes, context) -> bytes:
        return json.dumps({"status": "ok"}).encode()

    def _list(self, request: bytes, context) -> bytes:
        return json.dumps({"deployments": sorted(self._handles)}).encode()

    # -- lifecycle --------------------------------------------------------

    def start(self) -> None:
        import grpc
        from concurrent import futures

        handler = grpc.method_handlers_generic_handler(SERVICE, {
            "Predict": grpc.unary_unary_rpc_method_handler(
                self._predict, request_deserializer=_ident,
                response_serializer=_ident),
            "PredictStream": grpc.unary_stream_rpc_method_handler(
                self._predict_stream, request_deserializer=_ident,
                response_serializer=_ident),
            "Healthz": grpc.unary_unary_rpc_method_handler(
                self._healthz, request_deserializer=_ident,
                response_serializer=_ident),
            "ListDeployments": grpc.unary_unary_rpc_method_handler(
                self._list, request_deserializer=_ident,
                response_serializer=_ident),
        })
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=self.max_workers,
                                       thread_name_prefix="serve-grpc"))
        self._server.add_generic_rpc_handlers((handler,))
        self.port = self._server.add_insecure_port(
            f"{self.host}:{self.port}")
        self._server.start()

    def stop(self) -> None:
        if self._server is not None:
            self._server.stop(grace=0.5)
            self._server = None
