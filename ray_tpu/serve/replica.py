"""ReplicaActor: wraps the user's deployment callable.

Role analog: ``python/ray/serve/_private/replica.py:231`` (``ReplicaActor``
+ ``UserCallableWrapper :737``). A replica is an actor; requests arrive as
ordinary actor calls. TPU angle: a replica that owns TPU chips loads a
jitted model once in ``__init__`` and every request hits the compiled
function — batched inference composes with ``@serve.batch``.
"""

from __future__ import annotations

import inspect
import time
from typing import Any, Dict, Optional


class ReplicaActor:
    def __init__(self, cls_or_fn, init_args, init_kwargs,
                 user_config: Optional[Dict[str, Any]] = None,
                 handle_args: Optional[Dict[str, Any]] = None,
                 deployment_name: Optional[str] = None):
        # handle_args: deployment-name -> handle for composed models
        self._is_function = inspect.isfunction(cls_or_fn) or \
            inspect.isbuiltin(cls_or_fn)
        if self._is_function:
            self._callable = cls_or_fn
        else:
            self._callable = cls_or_fn(*init_args, **init_kwargs)
        self._user_config = user_config
        if user_config is not None and hasattr(self._callable, "reconfigure"):
            self._callable.reconfigure(user_config)
        self._num_requests = 0
        self._start_time = time.time()
        self._deployment_name = deployment_name
        # load-report push loop (ISSUE 12 routing signal): deployments
        # exposing load_state() advertise {inflight, kv_free, kv_total}
        # to the controller on a fixed cadence — capture our actor id NOW
        # (current_actor_id is task-context-local; the push thread runs
        # outside any task)
        if (deployment_name is not None
                and hasattr(self._callable, "load_state")):
            from ray_tpu import config as _cfg

            interval = float(_cfg.get("serve_load_report_interval_s"))
            if interval > 0:
                import threading

                import ray_tpu

                aid = ray_tpu.get_runtime_context().get_actor_id()
                self._push_thread = threading.Thread(
                    target=self._push_loop,
                    args=(interval, bytes.fromhex(aid) if aid else b""),
                    daemon=True, name="replica-load-report")
                self._push_thread.start()

    def _push_loop(self, interval: float, actor_id: bytes) -> None:
        import ray_tpu
        from ray_tpu.serve.controller import CONTROLLER_NAME

        ctrl = None
        # daemon thread: dies with the replica process (kill/scale-down);
        # there is no graceful-stop path to wire it into
        while True:
            time.sleep(interval)
            try:
                if ctrl is None:
                    ctrl = ray_tpu.get_actor(CONTROLLER_NAME)
                load = self._callable.load_state()
                ctrl.report_replica_load.remote(
                    self._deployment_name, actor_id, load)
            except Exception:
                # controller restarted / unreachable: re-resolve next
                # beat — a stale routing signal, never a dead replica
                ctrl = None

    def handle_request(self, method_name: str, args, kwargs):
        self._num_requests += 1
        if self._is_function:
            fn = self._callable
        else:
            fn = getattr(self._callable, method_name or "__call__")
        from ray_tpu.util import tracing

        if not tracing.tracing_enabled():
            out = fn(*args, **kwargs)
        else:
            # nests under the worker's execute span (thread-local), so
            # the serve request trace separates replica user-code time
            # from the actor-call machinery around it
            with tracing.span("serve.replica::execute",
                              {"method": method_name or "__call__"}):
                out = fn(*args, **kwargs)
        if inspect.iscoroutine(out):
            import asyncio

            out = asyncio.get_event_loop().run_until_complete(out)
        return out

    def handle_request_packed(self, request):
        """Compiled-DAG entry point (r13): the DAG edge carries ONE value,
        so the (method, args, kwargs) triple arrives packed."""
        method_name, args, kwargs = request
        return self.handle_request(method_name, args, kwargs)

    def reconfigure(self, user_config: Dict[str, Any]):
        self._user_config = user_config
        if hasattr(self._callable, "reconfigure"):
            self._callable.reconfigure(user_config)

    def check_health(self) -> bool:
        if hasattr(self._callable, "check_health"):
            self._callable.check_health()
        return True

    def stats(self) -> Dict[str, Any]:
        return {
            "num_requests": self._num_requests,
            "uptime_s": time.time() - self._start_time,
        }
