"""ray_tpu.serve — online serving over replica actors.

Role analog: ``python/ray/serve`` (SURVEY §2.5, §3.6). Control plane =
named controller actor reconciling replica actors; data plane = handle →
power-of-two-choices routing → replica actor call; plus dynamic batching,
model composition, multiplexing, autoscaling, and an HTTP proxy. TPU
angle: a replica owns chips and serves a jitted model; ``@serve.batch``
aggregates requests into MXU-sized batches.
"""

from ray_tpu.serve.api import (
    delete,
    get_deployment_handle,
    get_multiplexed_model_id,
    multiplexed,
    run,
    shutdown,
    start_http_proxy,
    status,
)
from ray_tpu.serve.admission import (AdmissionController,
                                     DeadlineExceededError,
                                     RequestShedError, SLOConfig)
from ray_tpu.serve.batching import batch
from ray_tpu.serve.kv_cache import BlockPool, PrefixCache
from ray_tpu.serve.llm import KVExport, LLMDeployment, LLMEngine
from ray_tpu.serve.multiplex import (ModelRegistry,
                                     MultiplexedLLMDeployment,
                                     SpeculativeLLMDeployment,
                                     SpeculativeLLMEngine)
from ray_tpu.serve.disagg import DisaggHandle, deploy_disagg
from ray_tpu.serve.kv_transfer import KVTransferError
from ray_tpu.serve.deployment import (
    Application,
    AutoscalingConfig,
    Deployment,
    DeploymentConfig,
    deployment,
)
from ray_tpu.serve.handle import DeploymentHandle, DeploymentResponse
from ray_tpu.serve.grpc_proxy import GrpcProxy
from ray_tpu.serve.proxy import HTTPProxy

__all__ = [
    "run",
    "start_http_proxy",
    "shutdown",
    "delete",
    "status",
    "deployment",
    "Deployment",
    "DeploymentConfig",
    "AutoscalingConfig",
    "Application",
    "DeploymentHandle",
    "DeploymentResponse",
    "HTTPProxy",
    "GrpcProxy",
    "batch",
    "LLMDeployment",
    "LLMEngine",
    "KVExport",
    "ModelRegistry",
    "MultiplexedLLMDeployment",
    "SpeculativeLLMDeployment",
    "SpeculativeLLMEngine",
    "DisaggHandle",
    "deploy_disagg",
    "KVTransferError",
    "BlockPool",
    "PrefixCache",
    "SLOConfig",
    "AdmissionController",
    "RequestShedError",
    "DeadlineExceededError",
    "multiplexed",
    "get_multiplexed_model_id",
    "get_deployment_handle",
]
