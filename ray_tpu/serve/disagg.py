"""Disaggregated prefill/decode serving (ISSUE 13 tentpole).

Splits :class:`~ray_tpu.serve.llm.LLMDeployment` serving into TWO
replica pools — a **prefill pool** that runs chunked prefill into paged
KV blocks and a **decode pool** that adopts the shipped blocks and
emits tokens — so a long prompt arriving never steals step time from
in-flight decodes: TTFT becomes prefill time plus one block-batch
transfer, and TPOT stops degrading under mixed traffic. The shipping
plane is :mod:`ray_tpu.serve.kv_transfer` (DeviceChannel rings on a
shared host, chunk-parallel store pulls across nodes) — the TPU analog
of the reference's NCCL channels inside compiled DAGs (PAPER.md L4).

The router (:class:`DisaggHandle`) is transfer-aware:

- **prompts go to prefill capacity**: power-of-two-choices over the
  prefill pool's queue depths (the handle's runtime load view);
- **sessions go to decode capacity**: power-of-two-choices over the
  decode pool's controller-mediated load reports (KV-claimable blocks +
  in-flight streams), with a configurable penalty for decode replicas
  on a DIFFERENT host than the chosen prefill replica (a channel hop
  beats a store hop);
- **admission budgets across both pools**: a request whose KV table
  could not fit the best decode replica's claimable blocks is shed at
  the router (``RequestShedError`` reason ``decode_kv``) before any
  prefill compute is spent; prefill-side SLO admission still applies in
  the engine.

Replica death at any stage re-routes: a dead prefill replica re-prefills
on a peer (nothing was delivered, so the decode pool adopts nothing
partial); a dead decode replica re-prefills too (the shipped payload
died with it — block refcounts are per-engine, so nothing leaks).
"""

from __future__ import annotations

import time
import uuid
from typing import Any, Dict, Iterator, List, Optional

from ray_tpu.serve.admission import RequestShedError
from ray_tpu.serve.handle import DeploymentHandle

#: ignore a load report older than this (dead/stalled replica)
_LOAD_STALE_S = 5.0


def deploy_disagg(model: str = "llama-debug", *, name: str = "llm",
                  prefill_replicas: int = 1, decode_replicas: int = 1,
                  max_concurrency: int = 16,
                  slo: Optional[Any] = None,
                  decode_slo: Optional[Any] = None,
                  prefill_actor_options: Optional[Dict[str, Any]] = None,
                  decode_actor_options: Optional[Dict[str, Any]] = None,
                  prefill_engine_kwargs: Optional[Dict[str, Any]] = None,
                  decode_engine_kwargs: Optional[Dict[str, Any]] = None,
                  **engine_kwargs) -> "DisaggHandle":
    """Deploy the two pools and return the routing handle. Engine
    kwargs (max_slots/max_len/block_size/prefill_chunk/...) apply to
    both pools, with the per-role engine kwargs layered on top (the
    pools genuinely want different tuning — prefill holds only the
    transient working set of in-flight prompts, decode keeps sessions +
    the prefix cache, so e.g. ``num_blocks`` splits asymmetrically);
    ``slo`` arms the prefill engines' admission gate, ``decode_slo``
    the decode engines' (defaults to ``slo``); the per-role actor
    options override the defaults (placement: pin a pool to a node
    with a scheduling strategy)."""
    from ray_tpu import serve
    from ray_tpu.serve.llm import LLMDeployment

    base = {"max_concurrency": max_concurrency, "num_cpus": 0}
    apps = {}
    for role, n, role_slo, extra, role_kw in (
            ("prefill", prefill_replicas, slo, prefill_actor_options,
             prefill_engine_kwargs),
            ("decode", decode_replicas,
             decode_slo if decode_slo is not None else slo,
             decode_actor_options, decode_engine_kwargs)):
        dep = serve.deployment(
            LLMDeployment, name=f"{name}-{role}", num_replicas=n,
            ray_actor_options=dict(base, **(extra or {})))
        apps[role] = serve.run(
            dep.bind(model, role=role, slo=role_slo,
                     **dict(engine_kwargs, **(role_kw or {}))),
            name=f"{name}-{role}")
    return DisaggHandle(apps["prefill"], apps["decode"])


class DisaggHandle:
    """Client-side router over one prefill pool and one decode pool."""

    def __init__(self, prefill: DeploymentHandle,
                 decode: DeploymentHandle):
        self.prefill = prefill
        self.decode = decode
        import random

        self._rng = random.Random()

    # -- load views --------------------------------------------------------

    @staticmethod
    def _pool_loads(handle: DeploymentHandle) -> Dict[bytes, dict]:
        """The handle's own TTL'd controller load view (shared with its
        routing path — claim-the-window-before-RPC plus empty-view
        backoff, so a wedged controller costs one probe per TTL window
        across ALL concurrent streams, never a probe pileup)."""
        return handle._kv_view()

    @staticmethod
    def _fresh(loads: Dict[bytes, dict]) -> Dict[bytes, dict]:
        cutoff = time.time() - _LOAD_STALE_S
        return {k: v for k, v in loads.items()
                if v.get("ts", 0) >= cutoff}

    # -- picking -----------------------------------------------------------

    @staticmethod
    def _refresh_safe(handle: DeploymentHandle) -> None:
        """Refresh the replica table, but route on the existing (stale)
        table rather than fail the request when the controller RPC
        hiccups mid-flight."""
        try:
            handle._refresh()
        except Exception:
            if not handle._replicas:
                raise

    def _pick_prefill(self, exclude: Optional[bytes] = None):
        """Prompts go to prefill capacity: the handle's own p2c over
        runtime queue depths (+ the dead-pick exclusion)."""
        self._refresh_safe(self.prefill)
        if not self.prefill._replicas:
            raise RuntimeError("prefill pool has no replicas")
        idx = self.prefill._pick_replica(exclude=exclude)
        return self.prefill._replicas[idx]

    def _pick_decode(self, prefer_node: Optional[str],
                     exclude: Optional[bytes] = None):
        """Sessions go to decode capacity: p2c over (inflight + weighted
        KV occupancy) from the load reports, plus a cross-node penalty
        so same-host transfers (channel path) win ties."""
        from ray_tpu import config as _cfg

        self._refresh_safe(self.decode)
        reps = self.decode._replicas
        if not reps:
            raise RuntimeError("decode pool has no replicas")
        cand = list(range(len(reps)))
        if exclude is not None and len(cand) > 1:
            cand = [i for i in cand
                    if reps[i]._actor_id.binary() != exclude] or cand
        if len(cand) == 1:
            return reps[cand[0]]
        loads = self._fresh(self._pool_loads(self.decode))
        w_kv = float(_cfg.get("serve_kv_route_weight"))
        w_x = float(_cfg.get("serve_disagg_cross_node_penalty"))

        def score(i: int) -> float:
            rep = loads.get(reps[i]._actor_id.binary())
            if not rep:
                return 0.0  # unknown: neutral (cold replica)
            s = float(rep.get("inflight", 0))
            total = rep.get("kv_total") or 0
            if total:
                s += w_kv * (1.0 - rep.get("kv_free", 0) / total)
            if prefer_node and rep.get("node") \
                    and rep["node"] != prefer_node:
                s += w_x
            return s

        i, j = self._rng.sample(cand, 2)
        return reps[i] if score(i) <= score(j) else reps[j]

    def _budget_check(self, n_prompt: int, max_new: int) -> None:
        """Cross-pool admission: shed NOW if no decode replica could
        claim this request's KV table (prefilling it would burn compute
        on a stream that can never start)."""
        loads = self._fresh(self._pool_loads(self.decode))
        sized = [l for l in loads.values()
                 if l.get("kv_total") and l.get("block_size")]
        if not sized:
            return  # no reports yet: the engines' own gates decide
        best = max(l["kv_free"] * l["block_size"] for l in sized)
        if n_prompt + max_new > best:
            raise RequestShedError(
                f"request shed (decode_kv): needs {n_prompt + max_new} "
                f"KV tokens but the best decode replica has {best} "
                "claimable", reason="decode_kv")

    # -- the request path --------------------------------------------------

    def stream(self, prompt_tokens, max_new_tokens: int = 16,
               eos: Optional[int] = None,
               deadline_s: Optional[float] = None) -> Iterator[int]:
        """One disaggregated request: prefill -> KV ship -> decode
        stream. Yields tokens (the prefill-sampled first token
        included). Replica deaths re-route within the configured retry
        budget; SLO sheds and deadline verdicts surface as-is."""
        from ray_tpu import config as _cfg
        from ray_tpu.util import tracing

        self._budget_check(len(prompt_tokens), max_new_tokens)
        req_span = tracing.manual_span(
            "serve.disagg::request",
            {"prompt_tokens": len(prompt_tokens),
             "max_new_tokens": max_new_tokens})
        tokens_out = 0
        try:
            retries = int(_cfg.get("serve_request_retries"))
            bad_prefill: Optional[bytes] = None
            bad_decode: Optional[bytes] = None
            attempt = 0
            while True:
                attempt += 1
                try:
                    for tok in self._attempt(
                            prompt_tokens, max_new_tokens, eos,
                            deadline_s, req_span, bad_prefill,
                            bad_decode):
                        tokens_out += 1
                        yield tok
                    return
                except _RetryableDeath as rd:
                    if rd.tokens_yielded or attempt > retries:
                        # a half-consumed stream cannot be spliced onto a
                        # fresh prefill, and the retry budget is bounded
                        raise rd.cause
                    bad_prefill, bad_decode = rd.bad_prefill, rd.bad_decode
        except BaseException as e:
            if req_span is not None:
                req_span.finish(error=repr(e))
                req_span = None
            raise
        finally:
            if req_span is not None:
                req_span.finish({"tokens": tokens_out})

    def _attempt(self, prompt_tokens, max_new_tokens, eos, deadline_s,
                 parent_span, bad_prefill, bad_decode):
        import ray_tpu
        from ray_tpu.core.exceptions import ActorDiedError
        from ray_tpu.util import tracing

        parent = parent_span.traceparent if parent_span else None
        prefill_rep = self._pick_prefill(exclude=bad_prefill)
        p_loads = self._fresh(self._pool_loads(self.prefill))
        p_node = (p_loads.get(prefill_rep._actor_id.binary()) or {}) \
            .get("node")
        decode_rep = self._pick_decode(p_node, exclude=bad_decode)
        req_id = uuid.uuid4().hex
        transfer = {"req": req_id,
                    "dst": decode_rep._actor_id.binary().hex(),
                    "dst_node": None}
        d_loads = self._fresh(self._pool_loads(self.decode))
        d_rec = d_loads.get(decode_rep._actor_id.binary())
        if d_rec:
            transfer["dst_node"] = d_rec.get("node")

        pre_span = tracing.manual_span(
            "serve.disagg::prefill", {"req": req_id}, parent=parent)
        try:
            desc = ray_tpu.get(
                prefill_rep.handle_request.remote(
                    "prefill_export",
                    (prompt_tokens, transfer, deadline_s), {}),
                timeout=float(_timeout(deadline_s)))
        except ActorDiedError as e:
            if pre_span is not None:
                pre_span.finish(error="prefill replica died")
            self._report_death(self.prefill, prefill_rep)
            raise _RetryableDeath(e, prefill_rep._actor_id.binary(),
                                  bad_decode, 0)
        except BaseException as e:
            if pre_span is not None:
                pre_span.finish(error=repr(e))
            raise
        if pre_span is not None:
            pre_span.finish({"kind": desc.get("kind", "?")})

        dec_span = tracing.manual_span(
            "serve.disagg::decode", {"req": req_id}, parent=parent)
        n = 0
        migrate = None
        try:
            it = iter(decode_rep.handle_request.options(
                num_returns="streaming").remote(
                "adopt_stream",
                (prompt_tokens, desc, max_new_tokens, eos, deadline_s),
                {}))
            while True:
                try:
                    ref = next(it)
                    tok = ray_tpu.get(ref)
                except StopIteration:
                    break
                except ActorDiedError as e:
                    self._report_death(self.decode, decode_rep)
                    raise _RetryableDeath(
                        e, bad_prefill, decode_rep._actor_id.binary(), n)
                # stream_batch > 1 replicas deliver token CHUNKS (lists)
                # — flatten so callers always consume per-token
                for t in (tok if isinstance(tok, list) else (tok,)):
                    if isinstance(t, dict) and "__migrate__" in t:
                        # replica drain (r20): the stream ends here; the
                        # session's KV already shipped to the named
                        # destination — splice the continuation below
                        # instead of aborting a half-consumed stream
                        migrate = t["__migrate__"]
                        continue
                    n += 1
                    yield t
            # drain splice: resume on the migration destination. Each
            # continuation re-emits the handoff token (adoption re-emits
            # ``first_token``, already delivered pre-drain) — drop it.
            # A continuation can itself be drained, so chase markers
            # until a stream ends without one (double preemption).
            while migrate is not None:
                mig, migrate = migrate, None
                dup_pending = True
                for tok in self._migrated_stream(mig, deadline_s):
                    for t in (tok if isinstance(tok, list) else (tok,)):
                        if isinstance(t, dict) and "__migrate__" in t:
                            migrate = t["__migrate__"]
                            continue
                        if dup_pending:
                            dup_pending = False
                            continue
                        n += 1
                        yield t
        except _RetryableDeath:
            if dec_span is not None:
                dec_span.finish(error="decode replica died")
            raise
        except BaseException as e:
            if dec_span is not None:
                dec_span.finish(error=repr(e))
            raise
        if dec_span is not None:
            dec_span.finish({"tokens": n})

    def _migrated_stream(self, mig: Dict[str, Any],
                         deadline_s: Optional[float]):
        """Open the continuation stream on a drain's migration
        destination: the replica adopts the shipped KV (the descriptor
        in ``mig``) against the fed-token transcript and keeps decoding
        — no re-prefill. The destination is addressed by actor id (the
        drain already chose it); routing policy does not re-pick."""
        import ray_tpu

        dst = mig["dst"]

        def find():
            return next((r for r in self.decode._replicas
                         if r._actor_id.binary().hex() == dst), None)

        self._refresh_safe(self.decode)
        rep = find()
        if rep is None:
            self.decode._refresh(force=True)
            rep = find()
        if rep is None:
            raise RuntimeError(
                f"session migrated to decode replica {dst[:8]} but it "
                "is not in the routing table")
        it = iter(rep.handle_request.options(
            num_returns="streaming").remote(
            "adopt_stream",
            (mig["prompt_tokens"], mig["desc"], mig["max_new_tokens"],
             mig["eos"], deadline_s), {}))
        while True:
            try:
                yield ray_tpu.get(next(it))
            except StopIteration:
                return

    @staticmethod
    def _report_death(handle: DeploymentHandle, replica) -> None:
        try:
            handle._replica_died(replica)
        except Exception:
            pass

    # -- elastic drain (r20) -----------------------------------------------

    def drain_decode_replica(self, actor_id_hex: Optional[str] = None,
                             *, node_id: Optional[str] = None,
                             timeout_s: float = 30.0) -> Dict[str, Any]:
        """Drain live sessions off a decode replica ahead of preemption:
        every in-flight decode ships its KV blocks to a surviving peer
        (round-robin over the rest of the pool) and its stream splices
        the continuation there — no re-prefill. This is the serving
        half of the elastic churn story: call it when the preemption
        notice lands, BEFORE the node-drain RPC
        (``rpc_node_drain`` → GCS "drained" death) kills the replica.

        Pick the victim by ``actor_id_hex``, or by ``node_id`` (drains
        every decode replica reported on that node — the shape a
        node-level preemption notice arrives in). Returns the merged
        drain report ``{sessions, migrated, failed, finished}``."""
        import ray_tpu

        self.decode._refresh(force=True)
        reps = self.decode._replicas
        loads = self._fresh(self._pool_loads(self.decode))

        def rec(r):
            return loads.get(r._actor_id.binary()) or {}

        if actor_id_hex is not None:
            victims = [r for r in reps
                       if r._actor_id.binary().hex() == actor_id_hex]
            if not victims:
                raise ValueError(
                    f"decode replica {actor_id_hex[:8]} not found")
        elif node_id is not None:
            victims = [r for r in reps if rec(r).get("node") == node_id]
            if not victims:
                return {"sessions": 0, "migrated": 0, "failed": 0,
                        "finished": 0}
        else:
            raise ValueError("pass actor_id_hex or node_id")
        victim_ids = {v._actor_id.binary() for v in victims}
        survivors = [r for r in reps
                     if r._actor_id.binary() not in victim_ids]
        if not survivors:
            raise RuntimeError(
                "no surviving decode replica to migrate sessions to")
        dests = [{"dst": r._actor_id.binary().hex(),
                  "dst_node": rec(r).get("node")} for r in survivors]
        total = {"sessions": 0, "migrated": 0, "failed": 0,
                 "finished": 0}
        for v in victims:
            rep_out = ray_tpu.get(
                v.handle_request.remote("drain_sessions",
                                        (dests, timeout_s), {}),
                timeout=timeout_s + 60.0)
            for k in total:
                total[k] += rep_out.get(k, 0)
        return total

    # -- introspection / lifecycle -----------------------------------------

    def kv_states(self) -> Dict[str, List[Dict[str, Any]]]:
        """Per-replica engine KV state for both pools (leak audits)."""
        import ray_tpu

        out: Dict[str, List[Dict[str, Any]]] = {}
        for role, h in (("prefill", self.prefill),
                        ("decode", self.decode)):
            h._refresh(force=True)
            out[role] = [
                ray_tpu.get(r.handle_request.remote("kv_state", (), {}),
                            timeout=60)
                for r in h._replicas]
        return out

    def shutdown(self) -> None:
        from ray_tpu import serve

        for h in (self.prefill, self.decode):
            try:
                serve.delete(h.deployment_name)
            except Exception:
                pass


class _RetryableDeath(Exception):
    """Internal: a replica died during an attempt; carries which pick to
    exclude on the retry and whether tokens already reached the caller."""

    def __init__(self, cause, bad_prefill, bad_decode, tokens_yielded):
        super().__init__(str(cause))
        self.cause = cause
        self.bad_prefill = bad_prefill
        self.bad_decode = bad_decode
        self.tokens_yielded = tokens_yielded


def _timeout(deadline_s: Optional[float]) -> float:
    base = 120.0
    return base if deadline_s is None else min(base, deadline_s + 5.0)
