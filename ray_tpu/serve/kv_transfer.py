"""KV-block shipping between prefill and decode pools (ISSUE 13).

The data plane of disaggregated serving — the TPU analog of the
reference's NCCL channels inside compiled DAGs (PAPER.md L4,
``dag/compiled_dag_node.py:278``): a prefill replica finishes a prompt,
gathers its KV blocks off the paged pool (:class:`~ray_tpu.serve.llm.
KVExport`), and ships them to the decode replica that will own the
stream. Two paths, picked per transfer by node identity:

- **channel** (both replicas share a host): the payload rides one slot
  of a multi-slot seq-numbered :class:`~ray_tpu.experimental.
  device_channel.DeviceChannel` ring as a
  :class:`~ray_tpu.experimental.device_channel.TensorWithMeta` — raw
  tensor body, 64B-aligned, no pickling; one memcpy into shm on the
  prefill side, one out on the decode side. One ring per
  (prefill replica, decode replica) pair, created lazily by the sender
  and demuxed by request id on the receiver (ring order is write order,
  not completion order). A full ring (decode replica wedged or dead)
  fails over to the store path instead of blocking prefill.
- **store** (cross-node): the payload is ``ray_tpu.put`` as ONE
  block-major array and the decode replica pulls it through the store's
  chunk-parallel transfer path; the block stride is registered as a
  pull-alignment hint (``util.state.hint_object_pull_align``) so every
  chunk carries whole KV blocks (block-batch framing on the existing
  chunked-pull path).

Payload layout is **block-major** ``[n_blocks, 2, L, bs, kvh, hd]``
(k/v stacked per block) so one block is one contiguous record — that is
what makes chunk alignment meaningful and keeps a torn transfer
impossible to adopt by construction: the decode engine scatters only a
complete batch delivered by a complete descriptor.

Failure seam: ``failpoints.hit("serve.kv_transfer", <req_id>)`` fires
before anything is shipped — the chaos matrix kills a prefill replica
here and asserts the request re-routes with zero leaked blocks or ring
slots on any live replica.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional, Tuple

import numpy as np

from ray_tpu.serve.llm import KVExport


class KVTransferError(RuntimeError):
    """A KV-block transfer could not be completed (payload never
    arrived, geometry mismatch, or the channel/store path failed)."""

    error_type = "kv_transfer"


_METRICS: Any = 0  # unresolved sentinel (None = resolved-unavailable)


def _metrics():
    global _METRICS
    if _METRICS == 0:  # resolve once, not per transfer (hot path)
        try:
            from ray_tpu.util import metric_defs as md

            _METRICS = {
                "bytes": md.get("rtpu_serve_kv_transfer_bytes_total"),
                "transfers": md.get("rtpu_serve_kv_transfers_total"),
                "seconds": md.get("rtpu_serve_kv_transfer_seconds"),
            }
        except Exception:  # metrics plane unavailable (bare unit tests)
            _METRICS = None
    return _METRICS


def _observe(path: str, nbytes: int, seconds: float) -> None:
    m = _metrics()
    if m:
        tags = {"path": path}
        m["bytes"].inc(nbytes, tags=tags)
        m["transfers"].inc(tags=tags)
        m["seconds"].observe(seconds, tags=tags)


def channel_name(src_id: str, dst_id: str) -> str:
    """Ring name for one (prefill, decode) pair. Prefixed with the
    creating runtime's session id so the owning runtime's shutdown
    sweep (``rtpu-chan-<session>-*``) reclaims the shm segment even
    when the replica dies without a graceful close — replicas are
    killed, never asked to clean up."""
    try:
        import ray_tpu

        session = ray_tpu.get_runtime_context().get_session_id()
    except Exception:
        session = "nosess"
    return f"{session}-kvx-{src_id}-{dst_id}"


def pack_export(export: KVExport) -> Tuple[Dict[str, Any], np.ndarray]:
    """(meta, block-major array) for one export. The array is
    ``[n_blocks, 2, L, bs, kvh, hd]`` — contiguous per block."""
    k, v = export.kv["k"], export.kv["v"]
    arr = np.ascontiguousarray(
        np.moveaxis(np.stack([k, v], axis=0), 2, 0))
    meta = {
        "token": int(export.token),
        "prompt_len": int(export.prompt_len),
        "block_size": int(export.block_size),
        "n_blocks": int(arr.shape[0]),
    }
    return meta, arr


def unpack_payload(meta: Dict[str, Any],
                   arr: np.ndarray) -> Dict[str, np.ndarray]:
    """Invert :func:`pack_export` back to the engine's adopt layout
    ([L, n, bs, kvh, hd] per tensor)."""
    if arr.ndim != 6 or arr.shape[0] != meta["n_blocks"]:
        raise KVTransferError(
            f"KV payload shape {arr.shape} does not match descriptor "
            f"({meta.get('n_blocks')} blocks)")
    kv = np.moveaxis(arr, 0, 2)  # [2, L, n, bs, kvh, hd]
    return {"k": kv[0], "v": kv[1]}


class KVSender:
    """Prefill-side shipper: one DeviceChannel ring per decode peer on
    the same host (lazily created, cached), store put for remote peers.
    ``ship`` returns the transfer DESCRIPTOR the router forwards to the
    decode replica — the payload itself never touches the router."""

    def __init__(self, src_id: str, *, max_payload_bytes: int,
                 slots: int = 4):
        self.src_id = src_id
        self.max_payload_bytes = int(max_payload_bytes)
        self.slots = slots
        self._chans: Dict[str, Any] = {}
        # the ring is SINGLE-writer: a replica's concurrent request
        # threads must serialize their writes per channel (two threads
        # racing write() would claim the same seq and clobber one
        # payload)
        self._wlocks: Dict[str, threading.Lock] = {}
        self._lock = threading.Lock()

    def _channel(self, dst_id: str):
        from ray_tpu.experimental.device_channel import DeviceChannel

        with self._lock:
            ch = self._chans.get(dst_id)
            if ch is None:
                # slot must hold payload + pickled meta header + padding
                ch = DeviceChannel(channel_name(self.src_id, dst_id),
                                   capacity=self.max_payload_bytes + 4096,
                                   create=True, slots=self.slots)
                self._chans[dst_id] = ch
                self._wlocks[dst_id] = threading.Lock()
            return ch, self._wlocks[dst_id]

    def ship(self, export: KVExport, *, req_id: str, dst_id: str,
             same_host: bool, timeout: float = 10.0) -> Dict[str, Any]:
        """Move one export toward ``dst_id``; returns the descriptor to
        hand to the decode replica's adopt call."""
        from ray_tpu.util import failpoints

        failpoints.hit("serve.kv_transfer", req_id)
        meta, arr = pack_export(export)
        meta["req"] = req_id
        t0 = time.perf_counter()
        if same_host:
            from ray_tpu.experimental.channel import (ChannelFullError,
                                                      ChannelTimeoutError)
            from ray_tpu.experimental.device_channel import TensorWithMeta

            try:
                ch, wlock = self._channel(dst_id)
                with wlock:
                    ch.write(TensorWithMeta(meta, arr), timeout=timeout)
                _observe("channel", arr.nbytes, time.perf_counter() - t0)
                return {"kind": "channel", "channel": ch.name,
                        "meta": meta}
            except (ChannelFullError, ChannelTimeoutError):
                # decode side wedged or slow to drain: the store path has
                # no ring bound — degrade rather than stall prefill
                pass
        import ray_tpu

        try:
            ref = ray_tpu.put(arr)
        except Exception:
            if not same_host:
                raise
            # no object store (in-process harness, no runtime): the
            # only degrade left is to BLOCK on the ring until the
            # decode side drains a slot — still bounded, and a typed
            # error beats a RuntimeError out of ray_tpu.put
            from ray_tpu.experimental.channel import (ChannelFullError,
                                                      ChannelTimeoutError)
            from ray_tpu.experimental.device_channel import \
                TensorWithMeta

            ch, wlock = self._channel(dst_id)
            try:
                with wlock:
                    ch.write(TensorWithMeta(meta, arr), timeout=60.0)
            except (ChannelFullError, ChannelTimeoutError) as e:
                raise KVTransferError(
                    f"KV ring to {dst_id} stayed full and no object "
                    "store is available") from e
            _observe("channel", arr.nbytes, time.perf_counter() - t0)
            return {"kind": "channel", "channel": ch.name, "meta": meta}
        _observe("store", arr.nbytes, time.perf_counter() - t0)
        return {"kind": "ref", "ref": ref, "meta": meta,
                "stride": arr.nbytes // max(arr.shape[0], 1),
                # records start AFTER the serialized header: the puller
                # anchors chunk boundaries at size - payload_bytes
                "payload_bytes": arr.nbytes}

    def close(self) -> None:
        with self._lock:
            chans, self._chans = list(self._chans.values()), {}
        for ch in chans:
            try:
                ch.unlink()
            except Exception:
                pass


class KVReceiver:
    """Decode-side fetcher. Channel payloads arrive in WRITE order on a
    per-sender ring while adopt calls arrive in routing order — so reads
    demux by request id: each fetch drains the ring under the channel's
    lock, parking batches for other requests until their fetch comes.
    Parked entries expire (their request died with its prefill replica)
    so an abandoned payload can never pin host memory forever."""

    _PARK_TTL_S = 60.0

    def __init__(self):
        self._chans: Dict[str, Any] = {}
        self._locks: Dict[str, threading.Lock] = {}
        self._parked: Dict[str, Tuple[float, Dict[str, Any],
                                      np.ndarray]] = {}
        self._lock = threading.Lock()

    def _attach(self, name: str):
        from ray_tpu.experimental.device_channel import DeviceChannel

        with self._lock:
            ch = self._chans.get(name)
            if ch is None:
                ch = DeviceChannel(name, create=False)
                self._chans[name] = ch
                self._locks[name] = threading.Lock()
            return ch, self._locks[name]

    def _prune_parked(self, now: float) -> None:
        with self._lock:
            dead = [k for k, (ts, _m, _a) in self._parked.items()
                    if now - ts > self._PARK_TTL_S]
            for k in dead:
                self._parked.pop(k, None)

    def fetch(self, desc: Dict[str, Any], *, timeout: float = 30.0
              ) -> Tuple[Dict[str, Any], Dict[str, np.ndarray]]:
        """Block until this descriptor's payload is in hand; returns
        ``(meta, kv)`` in the engine's adopt layout."""
        t0 = time.perf_counter()
        self._prune_parked(time.monotonic())  # orphan TTL: every fetch
        meta = desc["meta"]
        req = meta.get("req")
        if desc["kind"] == "ref":
            import ray_tpu
            from ray_tpu.util import state

            state.hint_object_pull_align(desc["ref"].binary()
                                         if hasattr(desc["ref"], "binary")
                                         else desc["ref"],
                                         desc.get("stride", 1),
                                         desc.get("payload_bytes", 0))
            arr = ray_tpu.get(desc["ref"], timeout=timeout)
            kv = unpack_payload(meta, np.asarray(arr))
            _observe("store", arr.nbytes, time.perf_counter() - t0)
            return meta, kv
        if desc["kind"] != "channel":
            raise KVTransferError(f"unknown transfer kind {desc['kind']!r}")
        from ray_tpu.experimental.channel import ChannelTimeoutError

        ch, lock = self._attach(desc["channel"])
        deadline = time.monotonic() + timeout
        while True:
            now = time.monotonic()
            with self._lock:
                parked = self._parked.pop(req, None)
            if parked is not None:
                _ts, pmeta, arr = parked
                kv = unpack_payload(pmeta, arr)
                _observe("channel", arr.nbytes, time.perf_counter() - t0)
                return pmeta, kv
            if now > deadline:
                raise KVTransferError(
                    f"KV payload for request {req!r} never arrived on "
                    f"{desc['channel']} within {timeout}s (prefill "
                    "replica died mid-transfer?)")
            with lock:
                try:
                    val = ch.read(timeout=min(0.5, deadline - now))
                except ChannelTimeoutError:
                    val = None
            if val is None:
                # a long wait must still expire orphans it parked
                self._prune_parked(time.monotonic())
                continue
            got_meta = dict(val.meta)
            if got_meta.get("req") == req:
                kv = unpack_payload(got_meta, val.tensor)
                _observe("channel", val.tensor.nbytes,
                         time.perf_counter() - t0)
                return got_meta, kv
            with self._lock:
                self._parked[got_meta.get("req")] = (
                    time.monotonic(), got_meta, val.tensor)
            self._prune_parked(time.monotonic())

    def close(self) -> None:
        with self._lock:
            chans, self._chans = list(self._chans.values()), {}
            self._locks.clear()
            self._parked.clear()
        for ch in chans:
            try:
                ch.close()
            except Exception:
                pass
