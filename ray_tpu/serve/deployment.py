"""Deployment: declarative unit of serving.

Role analog: ``python/ray/serve/deployment.py`` — the ``@serve.deployment``
decorator produces a Deployment (user class/function + replica/autoscaling
config); ``.bind(*args)`` produces an Application node; ``serve.run`` hands
the app to the controller.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Tuple


@dataclass
class AutoscalingConfig:
    min_replicas: int = 1
    max_replicas: int = 10
    target_ongoing_requests: float = 2.0
    upscale_factor: float = 1.5
    downscale_factor: float = 0.7
    # KV-occupancy target (LLM deployments): scale up when the average
    # reported used-block fraction exceeds this; None = ongoing-requests
    # policy only
    target_kv_utilization: Optional[float] = None


@dataclass
class DeploymentConfig:
    num_replicas: int = 1
    max_ongoing_requests: int = 8
    autoscaling_config: Optional[AutoscalingConfig] = None
    ray_actor_options: Dict[str, Any] = field(default_factory=dict)
    user_config: Optional[Dict[str, Any]] = None
    health_check_period_s: float = 10.0
    # compiled execution plane (r13): steady-state requests route through
    # a compiled DAG per replica (shm channels, no per-call task
    # submission); replicas get a second concurrency slot so control
    # calls (health checks, reconfigure) stay reachable while the DAG
    # exec loop occupies the first
    compiled: bool = False


class Deployment:
    def __init__(self, func_or_class, name: str,
                 config: Optional[DeploymentConfig] = None):
        self.func_or_class = func_or_class
        self.name = name
        self.config = config or DeploymentConfig()

    def options(self, *, name: Optional[str] = None,
                num_replicas: Optional[int] = None,
                max_ongoing_requests: Optional[int] = None,
                autoscaling_config=None,
                ray_actor_options: Optional[Dict[str, Any]] = None,
                user_config: Optional[Dict[str, Any]] = None,
                compiled: Optional[bool] = None) -> "Deployment":
        import copy

        cfg = copy.deepcopy(self.config)
        if num_replicas is not None:
            cfg.num_replicas = num_replicas
        if max_ongoing_requests is not None:
            cfg.max_ongoing_requests = max_ongoing_requests
        if autoscaling_config is not None:
            cfg.autoscaling_config = (
                AutoscalingConfig(**autoscaling_config)
                if isinstance(autoscaling_config, dict) else autoscaling_config)
        if ray_actor_options is not None:
            cfg.ray_actor_options = ray_actor_options
        if user_config is not None:
            cfg.user_config = user_config
        if compiled is not None:
            cfg.compiled = bool(compiled)
        return Deployment(self.func_or_class, name or self.name, cfg)

    def bind(self, *args, **kwargs) -> "Application":
        return Application(self, args, kwargs)

    def __repr__(self):
        return f"Deployment({self.name!r}, {self.config.num_replicas} replicas)"


@dataclass
class Application:
    """A bound deployment (possibly with other Applications as init args —
    model composition, reference ``deployment_graph_build.py``)."""

    deployment: Deployment
    init_args: Tuple = ()
    init_kwargs: Dict[str, Any] = field(default_factory=dict)

    def flatten(self) -> Dict[str, "Application"]:
        """All applications in the graph keyed by deployment name."""
        out = {self.deployment.name: self}
        for a in list(self.init_args) + list(self.init_kwargs.values()):
            if isinstance(a, Application):
                out.update(a.flatten())
        return out


def deployment(func_or_class=None, *, name: Optional[str] = None,
               num_replicas: int = 1, max_ongoing_requests: int = 8,
               autoscaling_config=None, ray_actor_options=None,
               user_config=None, compiled: bool = False):
    """``@serve.deployment`` decorator (reference ``serve/api.py``)."""

    def wrap(fc):
        cfg = DeploymentConfig(
            num_replicas=num_replicas,
            max_ongoing_requests=max_ongoing_requests,
            autoscaling_config=(AutoscalingConfig(**autoscaling_config)
                                if isinstance(autoscaling_config, dict)
                                else autoscaling_config),
            ray_actor_options=ray_actor_options or {},
            user_config=user_config,
            compiled=compiled,
        )
        return Deployment(fc, name or fc.__name__, cfg)

    if func_or_class is None:
        return wrap
    return wrap(func_or_class)
