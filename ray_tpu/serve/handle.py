"""DeploymentHandle + Router: the request path.

Role analog: ``python/ray/serve/handle.py:711`` → ``Router``
(``router.py:312``) → ``PowerOfTwoChoicesReplicaScheduler``
(``replica_scheduler/pow_2_scheduler.py:49``). The handle keeps a local
in-flight count per replica (the reference's client-side queue-length cache,
``common.py:218``) and picks the less-loaded of two random replicas; the
routing table refreshes from the controller when its version bumps.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional


@dataclass
class _AppRefSentinel:
    """Placeholder for a composed sub-application in init args."""

    name: str


class DeploymentResponse:
    """Future-like wrapper over the underlying ObjectRef (reference
    ``DeploymentResponse``)."""

    def __init__(self, ref, on_done=None):
        self._ref = ref
        self._on_done = on_done
        self._result = None
        self._done = False

    def result(self, timeout_s: Optional[float] = None):
        import ray_tpu

        if not self._done:
            self._result = ray_tpu.get(self._ref, timeout=timeout_s)
            self._done = True
            if self._on_done:
                self._on_done()
        return self._result

    @property
    def ref(self):
        return self._ref

    def __await__(self):
        return self._ref.__await__()


class DeploymentHandle:
    def __init__(self, deployment_name: str, controller=None,
                 method_name: str = "__call__"):
        self.deployment_name = deployment_name
        self._controller = controller
        self._method = method_name
        self._replicas: List[Any] = []
        self._version = -1
        self._max_ongoing = 8
        self._inflight: Dict[int, int] = {}
        self._rng = random.Random()

    # -- controller sync --------------------------------------------------

    def _get_controller(self):
        if self._controller is None:
            import ray_tpu

            self._controller = ray_tpu.get_actor("SERVE_CONTROLLER")
        return self._controller

    def _refresh(self, force: bool = False):
        import ray_tpu

        ctrl = self._get_controller()
        version = ray_tpu.get(ctrl.get_version.remote())
        if force or version != self._version or not self._replicas:
            info = ray_tpu.get(
                ctrl.get_routing_info.remote(self.deployment_name))
            if info is None:
                raise KeyError(
                    f"deployment {self.deployment_name!r} not found")
            self._replicas = info["replicas"]
            self._max_ongoing = info["max_ongoing_requests"]
            self._version = info["version"]
            self._inflight = {i: 0 for i in range(len(self._replicas))}

    # -- routing ----------------------------------------------------------

    def _pick_replica(self) -> int:
        n = len(self._replicas)
        if n == 1:
            return 0
        i, j = self._rng.sample(range(n), 2)
        return i if self._inflight.get(i, 0) <= self._inflight.get(j, 0) else j

    def options(self, *, method_name: Optional[str] = None
                ) -> "DeploymentHandle":
        h = DeploymentHandle(self.deployment_name, self._controller,
                             method_name or self._method)
        h._replicas = self._replicas
        h._version = self._version
        h._max_ongoing = self._max_ongoing
        h._inflight = self._inflight   # share the load view
        return h

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        self._refresh()
        idx = self._pick_replica()
        replica = self._replicas[idx]
        self._inflight[idx] = self._inflight.get(idx, 0) + 1
        ref = replica.handle_request.remote(self._method, args, kwargs)

        def _done(i=idx):
            self._inflight[i] = max(0, self._inflight.get(i, 0) - 1)
            self._report_metrics()

        return DeploymentResponse(ref, on_done=_done)

    def _report_metrics(self):
        try:
            ctrl = self._get_controller()
            total = float(sum(self._inflight.values()))
            ctrl.record_request_metrics.remote(self.deployment_name, total)
        except Exception:
            pass

    def __getattr__(self, name: str) -> "DeploymentHandle":
        if name.startswith("_"):
            raise AttributeError(name)
        return self.options(method_name=name)

    def __reduce__(self):
        return (DeploymentHandle, (self.deployment_name, None, self._method))
