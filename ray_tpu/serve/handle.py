"""DeploymentHandle + Router: the request path.

Role analog: ``python/ray/serve/handle.py:711`` → ``Router``
(``router.py:312``) → ``PowerOfTwoChoicesReplicaScheduler``
(``replica_scheduler/pow_2_scheduler.py:49``). Routing load comes from the
RUNTIME's actor queue depths (queued + in-flight calls per replica actor) —
the authoritative version of the reference's replica-reported queue-length
cache (``replica_scheduler/common.py:218``), shared by every handle in the
cluster instead of per-handle local guesses; a short-TTL cache plus local
in-flight deltas keeps the hot path cheap. Streaming responses
(``handle.options(stream=True)``) ride ``num_returns="streaming"`` actor
calls and yield results as the replica produces them (reference
``handle.py`` streaming / ``proxy.py`` chunked responses).
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional


@dataclass
class _AppRefSentinel:
    """Placeholder for a composed sub-application in init args."""

    name: str


class DeploymentResponse:
    """Future-like wrapper over the underlying ObjectRef (reference
    ``DeploymentResponse``). ``retry`` (set by the issuing handle) re-routes
    the request to another replica when this one died before replying —
    the router half of Serve's replica fault tolerance (the controller
    replaces the dead replica; see ``ServeController.report_replica_death``)."""

    def __init__(self, ref, on_done=None, retry=None):
        self._ref = ref
        self._on_done = on_done
        self._retry = retry
        self._result = None
        self._done = False

    def result(self, timeout_s: Optional[float] = None):
        import time

        import ray_tpu
        from ray_tpu.core.exceptions import ActorDiedError, GetTimeoutError

        if not self._done:
            # ONE deadline across every retry: a re-route must not restart
            # the caller's timeout (each attempt gets what remains)
            deadline = (None if timeout_s is None
                        else time.monotonic() + timeout_s)
            while True:
                remaining = (None if deadline is None
                             else max(0.0, deadline - time.monotonic()))
                try:
                    self._result = ray_tpu.get(self._ref, timeout=remaining)
                    break
                except ActorDiedError:
                    if self._retry is None:
                        raise
                    # reports the death, waits for a live replica, re-issues;
                    # raises (ending the loop) when retries are exhausted —
                    # the retry closes the in-flight accounting itself, so
                    # neutralize on_done/retry before propagating (a repeat
                    # result() call must not double-decrement)
                    try:
                        self._ref = self._retry()
                    except BaseException:
                        self._retry = None
                        self._on_done = None
                        raise
                except GetTimeoutError:
                    # NOT terminal: the replica is still executing this
                    # request — keep the routing slot held and the span
                    # open (a later result() call may still complete it)
                    raise
                except Exception:
                    # terminal failure (replica raised): the request is
                    # over — release its routing slot and finish its
                    # request span exactly once, then surface.
                    # (Exception, NOT BaseException: a KeyboardInterrupt
                    # in the waiting caller does not end the request —
                    # the replica is still executing it.)
                    cb = self._on_done
                    self._on_done = None
                    if cb:
                        cb()
                    raise
            self._done = True
            if self._on_done:
                self._on_done()
        return self._result

    @property
    def ref(self):
        return self._ref

    def __await__(self):
        return self._ref.__await__()


class DeploymentResponseGenerator:
    """Iterates a streaming deployment call, yielding RESULTS as the
    replica produces them (reference streaming DeploymentResponse)."""

    def __init__(self, ref_gen, on_done=None, retry=None):
        self._ref_gen = ref_gen
        self._on_done = on_done
        self._retry = retry
        self._finished = False
        self._yielded = False

    def __iter__(self):
        return self

    def __next__(self):
        import ray_tpu
        from ray_tpu.core.exceptions import ActorDiedError

        while True:
            try:
                ref = next(self._ref_gen)
                val = ray_tpu.get(ref)
            except StopIteration:
                self._finish()
                raise
            except ActorDiedError:
                # replica died: re-route, but only while the stream is
                # still splice-able (nothing yielded yet) — a half-consumed
                # stream cannot be transparently resumed on a new replica
                if self._retry is None or self._yielded:
                    self._finish()
                    raise
                try:
                    self._ref_gen = self._retry()
                except BaseException:
                    # exhausted: the retry closed the accounting itself
                    self._on_done = None
                    self._finish()
                    raise
                continue
            except BaseException:
                self._finish()
                raise
            self._yielded = True
            return val

    def _finish(self):
        if not self._finished:
            self._finished = True
            if self._on_done:
                self._on_done()


class _BrokenFuture:
    """Future for a request whose pipeline was already broken/torn at
    admission: ``get()`` re-raises, so the response's broken-DAG fallback
    runs lazily at ``result()`` time — ``remote()`` stays non-blocking."""

    def __init__(self, dag, err):
        self._dag = dag
        self._err = err

    def get(self, timeout: Optional[float] = None):
        raise self._err


class CompiledDeploymentResponse:
    """``DeploymentResponse`` analog for the compiled execution plane:
    wraps a :class:`CompiledDAGFuture` (no ``.ref`` — there is no object
    store entry on this path). A BROKEN pipeline (the routed replica died
    mid-DAG) falls back to one normally-routed actor call; a plain
    timeout propagates — re-executing a possibly non-idempotent request
    on timeout is the caller's decision, not the router's."""

    def __init__(self, fut, on_done=None, fallback=None):
        self._fut = fut
        self._on_done = on_done
        self._fallback = fallback
        self._done = False
        self._result = None

    def result(self, timeout_s: Optional[float] = None):
        if self._done:
            return self._result
        from ray_tpu.dag import DAGExecutionError

        try:
            val = self._fut.get(timeout=timeout_s)
        except DAGExecutionError:
            broken = getattr(self._fut._dag, "_broken", None) or \
                getattr(self._fut._dag, "_torn_down", False)
            if self._fallback is None or not broken:
                self._finish()
                raise
            try:
                val = self._fallback()
            except BaseException:
                self._finish()
                raise
        self._result = val
        self._done = True
        self._finish()
        return val

    def _finish(self):
        cb, self._on_done = self._on_done, None
        if cb:
            cb()

    def __await__(self):
        import asyncio

        return asyncio.get_running_loop().run_in_executor(
            None, self.result).__await__()


_DEPTH_TTL_S = 0.05
# replica-pushed KV/load reports (controller-mediated) are refreshed less
# often than runtime queue depths: they ride a controller round trip, and
# KV occupancy moves at decode speed, not per-request speed
_KV_TTL_S = 0.25
# a load report older than this is ignored (replica died or stopped
# pushing; depth-only routing beats steering by a ghost)
_KV_STALE_S = 5.0
# compiled fast path: routing-table staleness bound. The per-request
# controller round trip (get_version) is exactly the control-plane cost
# the compiled plane exists to remove; a stale table self-heals anyway
# (a dead replica's broken DAG triggers the fallback + forced refresh).
_COMPILED_REFRESH_TTL_S = 1.0

# Process-global compiled-pipeline cache, keyed by replica actor id: the
# method name rides the request payload, so ONE DAG per replica serves
# every method and every handle clone in this process — a second exec
# loop would burn the replica's spare concurrency slot (held for health
# checks). Compiled deployments are designed to be driven from one
# process (the steady-state server loop); handles pickled into OTHER
# processes start their own loop there and need the replica to have a
# free slot.
_dag_cache: Dict[bytes, Any] = {}
_dag_cache_lock = None  # created lazily (threading import stays local)


def _dag_lock():
    global _dag_cache_lock
    if _dag_cache_lock is None:
        import threading

        _dag_cache_lock = threading.Lock()
    return _dag_cache_lock


class DeploymentHandle:
    def __init__(self, deployment_name: str, controller=None,
                 method_name: str = "__call__", stream: bool = False):
        self.deployment_name = deployment_name
        self._controller = controller
        self._method = method_name
        self._stream = stream
        self._replicas: List[Any] = []
        self._version = -1
        self._max_ongoing = 8
        # shared load view: runtime queue depths (TTL-cached) + local
        # in-flight deltas since the last refresh
        self._depths: List[int] = []
        self._depth_ts = 0.0
        self._delta: Dict[int, int] = {}
        self._rng = random.Random()
        # KV-aware routing (ISSUE 12): replicas whose deployment exposes
        # load_state() push {kv_free, kv_total, inflight} to the
        # controller; the handle folds KV occupancy into the pick score.
        # MUTABLE state shared BY REFERENCE with options()/__getattr__
        # clones (a fresh clone per method-style call would otherwise
        # reset the TTL — one controller RPC per request — and freeze
        # the rr cursor): kv_loads, kv_next (monotonic), rr_next
        self._route_state: Dict[str, Any] = {
            "kv_loads": {}, "kv_next": 0.0, "rr_next": 0}
        # set from routing info: whether any replica has ever pushed a
        # load report. False = never probe the controller for KV state
        # (plain deployments must not pay even a rare blocking RPC on
        # their request path); the controller bumps the version on the
        # FIRST report, so handles refetch and flip this
        self._has_loads = False
        # compiled execution plane (r13): when the deployment opted in
        # (``compiled=True``), steady-state requests route through one
        # compiled DAG per replica (shm channels, zero per-call task
        # submission); pipelines live in the process-global _dag_cache
        # (one per replica, shared by every handle in this process),
        # lazily built, torn down when their replica leaves the table
        self._compiled = False
        self._refresh_ts = 0.0  # last successful _refresh (monotonic)
        self._dags = _dag_cache
        # multi-model routing (ISSUE 16): model_id steers toward replicas
        # advertising the model RESIDENT (a swap-in costs a weight
        # page-in) and rides the request as a kwarg; prefix_hint (the
        # request's prompt tokens, or a precomputed digest) steers
        # sessions sharing a system prompt to the replica whose prefix
        # trie already holds it
        self._model_id: Optional[str] = None
        self._prefix_hint: Optional[Any] = None

    # -- controller sync --------------------------------------------------

    def _get_controller(self):
        if self._controller is None:
            import ray_tpu

            self._controller = ray_tpu.get_actor("SERVE_CONTROLLER")
        return self._controller

    def _refresh(self, force: bool = False,
                 timeout: Optional[float] = None):
        import ray_tpu

        ctrl = self._get_controller()
        version = ray_tpu.get(ctrl.get_version.remote(), timeout=timeout)
        if force or version != self._version or not self._replicas:
            info = ray_tpu.get(
                ctrl.get_routing_info.remote(self.deployment_name),
                timeout=timeout)
            if info is None:
                raise KeyError(
                    f"deployment {self.deployment_name!r} not found")
            self._replicas = info["replicas"]
            self._max_ongoing = info["max_ongoing_requests"]
            self._version = info["version"]
            self._compiled = bool(info.get("compiled"))
            self._has_loads = bool(info.get("has_loads"))
            self._depths = [0] * len(self._replicas)
            self._depth_ts = 0.0
            self._delta = {i: 0 for i in range(len(self._replicas))}
            self._teardown_stale_dags()
        self._refresh_ts = time.monotonic()

    def _teardown_stale_dags(self) -> None:
        """Routing table changed: drop THIS deployment's compiled DAGs
        whose replica left the table (scaled down, replaced, or dead —
        keeping the DAG would pin the departed replica's exec loop and
        shm rings). Cache entries are tagged with their deployment name,
        so other deployments' pipelines are never touched."""
        if not self._compiled or not self._dags:
            return
        live = {r._actor_id.binary() for r in self._replicas}
        with _dag_lock():
            stale = [k for k, (dep, _d) in self._dags.items()
                     if dep == self.deployment_name and k not in live]
            dags = [self._dags.pop(k)[1] for k in stale]
        for dag in dags:
            try:
                dag.teardown(timeout=2.0)
            except Exception:
                pass

    def _dag_for(self, idx: int):
        """The replica's compiled request pipeline, built on first use:
        ``InputNode -> replica.handle_request_packed``, admitting up to
        ``max_ongoing_requests`` overlapping requests. Cached process-
        globally: one pipeline per replica, shared by every handle."""
        replica = self._replicas[idx]
        key = replica._actor_id.binary()
        with _dag_lock():
            ent = self._dags.get(key)
            if ent is None:
                from ray_tpu.dag import InputNode

                with InputNode() as inp:
                    node = replica.handle_request_packed.bind(inp)
                dag = node.experimental_compile(
                    max_in_flight=max(1, min(self._max_ongoing, 32)))
                ent = (self.deployment_name, dag)
                self._dags[key] = ent
        return ent[1]

    # -- routing ----------------------------------------------------------

    def _load_view(self) -> List[float]:
        now = time.monotonic()
        if now - self._depth_ts > _DEPTH_TTL_S:
            from ray_tpu.util import state

            try:
                ids = [r._actor_id.binary() for r in self._replicas]
                self._depths = state.actor_queue_depths(ids)
                self._delta = {i: 0 for i in range(len(self._replicas))}
                self._depth_ts = now
            except Exception:
                pass  # stale view beats no view
        if len(self._depths) != len(self._replicas):
            # cloned handle whose first refresh failed: all-zero view
            self._depths = [0] * len(self._replicas)
        return [self._depths[i] + self._delta.get(i, 0)
                for i in range(len(self._replicas))]

    def _kv_view(self) -> Dict[bytes, Dict[str, Any]]:
        """TTL-cached replica load reports (kv_free/kv_total/inflight)
        from the controller. Empty for deployments that don't advertise
        KV state — routing degrades to pure queue depth."""
        now = time.monotonic()
        rs = self._route_state
        if now >= rs["kv_next"]:
            import ray_tpu

            # claim the window BEFORE the RPC: concurrent callers that
            # race past a wedged controller must route on the stale view,
            # not pile up their own blocking probes (one probe per
            # window, ever)
            rs["kv_next"] = now + _KV_TTL_S
            try:
                ctrl = self._get_controller()
                rs["kv_loads"] = ray_tpu.get(
                    ctrl.get_replica_loads.remote(self.deployment_name),
                    timeout=2) or {}
            except Exception:
                pass  # stale view beats no view
            if not rs["kv_loads"]:
                # deployment doesn't advertise KV state (no load_state):
                # exponential backoff to 30s — a plain deployment must
                # not pay a recurring controller probe on its request
                # path forever (reset to the base TTL on the first
                # non-empty view)
                backoff = min(rs.get("kv_backoff", _KV_TTL_S) * 2, 30.0)
                rs["kv_backoff"] = backoff
                rs["kv_next"] = now + backoff
            else:
                rs["kv_backoff"] = _KV_TTL_S
        return rs["kv_loads"]

    def _scores(self) -> List[float]:
        """Per-replica routing score: runtime queue depth (+ local
        in-flight deltas) plus weighted KV occupancy — a replica about to
        run out of KV blocks is as bad a pick as a deep queue, even when
        its queue is short (admission there would shed or stall)."""
        from ray_tpu import config as _cfg

        load = [float(x) for x in self._load_view()]
        if not self._has_loads:
            return load
        kv = self._kv_view()
        if not kv:
            return load
        w = float(_cfg.get("serve_kv_route_weight"))
        mw = (float(_cfg.get("serve_model_route_weight"))
              if self._model_id is not None else 0.0)
        if w <= 0 and mw <= 0:
            return load
        now = time.time()
        for i, r in enumerate(self._replicas):
            rep = kv.get(r._actor_id.binary())
            if not rep or now - rep.get("ts", 0) > _KV_STALE_S:
                continue
            total = rep.get("kv_total") or 0
            if w > 0 and total > 0:
                used_frac = 1.0 - rep.get("kv_free", 0) / total
                load[i] += w * used_frac
            if mw > 0:
                models = rep.get("models")
                if models is not None:
                    # model residency folds into the p2c score: a
                    # replica that must page the weights in competes at
                    # a penalty, but can still win when the resident
                    # replicas are saturated
                    m = models.get(self._model_id)
                    if not m or m.get("state") != "hbm":
                        load[i] += mw
        return load

    def _affinity_key(self) -> Optional[str]:
        """Content digest of the request's first prompt block (the key
        replicas publish in their prefix digests), or None when no hint
        was given / the block geometry is unknown."""
        hint = self._prefix_hint
        if hint is None:
            return None
        if isinstance(hint, str):
            return hint  # precomputed digest
        bs = 0
        for rep in self._route_state["kv_loads"].values():
            bs = int(rep.get("block_size") or 0)
            if bs:
                break
        toks = list(hint)
        if not bs or len(toks) < bs:
            return None
        from ray_tpu.serve.kv_cache import prefix_key_digest

        return prefix_key_digest(toks[:bs])

    def _affinity_pick(self, cand: List[int],
                       score: List[float]) -> Optional[int]:
        """Cluster-wide prefix affinity: direct-pick the replica whose
        published prefix digest carries this request's first-block key —
        unless that replica is overloaded (its score trails the best
        candidate by more than the margin), in which case load wins and
        the pick falls through to p2c. A cold prefix falls through too;
        whoever serves it becomes the affinity home via its trie."""
        from ray_tpu import config as _cfg

        if not self._has_loads or self._prefix_hint is None:
            return None
        if not _cfg.get("serve_prefix_affinity"):
            return None
        kv = self._kv_view()
        if not kv:
            return None
        key = self._affinity_key()
        if key is None:
            return None
        now = time.time()
        best, best_w = None, -1
        for i in cand:
            rep = kv.get(self._replicas[i]._actor_id.binary())
            if not rep or now - rep.get("ts", 0) > _KV_STALE_S:
                continue
            for k, wgt in rep.get("prefix_digest", []):
                if k == key and wgt > best_w:
                    best, best_w = i, int(wgt)
        if best is None:
            # cold prefix: no replica has published it yet. Rendezvous-
            # hash the key over the candidates so every handle in the
            # cluster sends this tenant's opening burst to the SAME
            # replica — falling through to p2c scatters the prefix
            # across the fleet, planting one trie copy (and paying one
            # re-prefill) per replica it touches before any digest can
            # converge. Stable replica ids make independent handles
            # agree without coordination; the margin check below still
            # lets load override the hash.
            import hashlib
            best = max(cand, key=lambda i: hashlib.sha1(
                str(key).encode()
                + self._replicas[i]._actor_id.binary()).digest())
        margin = float(_cfg.get("serve_prefix_affinity_margin"))
        if score[best] > min(score[c] for c in cand) + margin:
            return None  # overloaded: affinity yields to load
        return best

    def _pick_replica(self, exclude: Optional[bytes] = None) -> int:
        """Power-of-two-choices over the combined load score;
        ``exclude`` bars a replica observed dead THIS request (the retry
        path must never re-pick its own victim while an alternative
        exists). RTPU_SERVE_ROUTING=rr forces plain round-robin (the
        bench A/B baseline)."""
        from ray_tpu import config as _cfg

        n = len(self._replicas)
        cand = list(range(n))
        if exclude is not None and n > 1:
            cand = [i for i in cand
                    if self._replicas[i]._actor_id.binary() != exclude] \
                or list(range(n))
        if len(cand) == 1:
            return cand[0]
        if str(_cfg.get("serve_routing")) == "rr":
            self._route_state["rr_next"] += 1
            return cand[self._route_state["rr_next"] % len(cand)]
        score = self._scores()
        aff = self._affinity_pick(cand, score)
        if aff is not None:
            return aff
        i, j = self._rng.sample(cand, 2)
        return i if score[i] <= score[j] else j

    def options(self, *, method_name: Optional[str] = None,
                stream: Optional[bool] = None,
                model_id: Optional[str] = None,
                prefix_hint: Optional[Any] = None) -> "DeploymentHandle":
        h = DeploymentHandle(self.deployment_name, self._controller,
                             method_name or self._method,
                             self._stream if stream is None else stream)
        h._replicas = self._replicas
        h._version = self._version
        h._max_ongoing = self._max_ongoing
        # the clone inherits a matching _version, so its _refresh() will
        # skip the info fetch — _compiled must travel with it or method
        # clones (handle.my_method) silently leave the compiled plane
        h._compiled = self._compiled
        h._has_loads = self._has_loads
        h._refresh_ts = self._refresh_ts
        # the SHARED routing-state object travels by reference:
        # __getattr__ makes a FRESH clone per method-style call, and a
        # clone-private copy would reset the KV-view TTL (one blocking
        # controller RPC per request) and freeze the rr cursor
        h._route_state = self._route_state
        h._model_id = model_id if model_id is not None else self._model_id
        h._prefix_hint = (prefix_hint if prefix_hint is not None
                          else self._prefix_hint)
        return h

    def _issue(self, args, kwargs, exclude: Optional[bytes] = None):
        """Pick a replica and dispatch one request to it."""
        self._refresh()
        if self._model_id is not None:
            # the routing hint doubles as the request's model address
            kwargs.setdefault("model_id", self._model_id)
        idx = self._pick_replica(exclude=exclude)
        replica = self._replicas[idx]
        self._delta[idx] = self._delta.get(idx, 0) + 1
        call = replica.handle_request
        if self._stream:
            call = call.options(num_returns="streaming")
        return idx, replica, call.remote(self._method, args, kwargs)

    def _replica_died(self, replica) -> None:
        """Report a dead replica to the controller (which drops it from the
        routing table and reconciles a replacement) and force-refresh this
        handle's view so the re-issue routes to a live replica."""
        import ray_tpu

        try:
            ctrl = self._get_controller()
            ray_tpu.get(ctrl.report_replica_death.remote(
                self.deployment_name, replica._actor_id.binary()),
                timeout=10)
        except Exception:
            pass  # controller unreachable: the forced refresh still helps
        try:
            self._refresh(force=True, timeout=10)
        except Exception:
            # dead/wedged controller must not break the retry path: the
            # cached replica list may still name a live replica, and the
            # bounded retry budget decides the outcome either way
            pass

    def remote(self, *args, **kwargs):
        if not self._stream:
            if self._version == -1:
                self._refresh()
            if self._compiled:
                # compiled execution plane: no task submission, no
                # scheduler — the request rides the replica's shm DAG
                # (None = payload can't ride the ring; fall through to
                # the ordinary actor-call path below)
                resp = self._remote_compiled(args, kwargs)
                if resp is not None:
                    return resp
        from ray_tpu import config as _cfg
        from ray_tpu.util import tracing

        # Trace chain (ISSUE 7): a manual request span covers the FULL
        # request lifetime (result()/stream drain happen on other threads,
        # where the thread-local span() context cannot be held open); the
        # route span below brackets replica selection + dispatch, so the
        # actor-call submit/execute spans nest under it and
        # summarize_critical_path(trace_id) reconciles route -> queue ->
        # execute -> stream against the measured latency.
        req_span = tracing.manual_span(
            "serve.handle::request", {"deployment": self.deployment_name})
        state = {}
        if req_span is None:
            state["idx"], state["replica"], ref = self._issue(args, kwargs)
        else:
            try:
                with tracing.span("serve.handle::route",
                                  {"deployment": self.deployment_name},
                                  parent=req_span.traceparent):
                    state["idx"], state["replica"], ref = self._issue(
                        args, kwargs)
            except BaseException as e:
                # a failed dispatch still records its request span (the
                # route span's parent must exist in the trace)
                req_span.finish(error=repr(e))
                raise
        retries = [int(_cfg.get("serve_request_retries"))]

        def _done():
            i = state["idx"]
            self._delta[i] = self._delta.get(i, 0) - 1
            self._report_metrics()
            if req_span is not None:
                req_span.finish({"replica_idx": state["idx"]})

        def _retry():
            # called when the routed-to replica died before replying:
            # report + re-route (bounded — a deployment whose replicas
            # keep dying must eventually surface the error). The re-issue
            # EXCLUDES the dead pick: _replica_died refreshes routing
            # state, but when the controller is unreachable the cached
            # table still lists the corpse — the retry must re-consult
            # state AND bar its own victim, never re-roll the same pick.
            retries[0] -= 1
            if retries[0] < 0:
                from ray_tpu.core.exceptions import ActorDiedError

                _done()  # the request is terminal: release its slot
                raise ActorDiedError(
                    f"deployment {self.deployment_name!r}: request still "
                    "failing after replica-death retries")
            self._delta[state["idx"]] = (
                self._delta.get(state["idx"], 0) - 1)
            dead = state["replica"]._actor_id.binary()
            self._replica_died(state["replica"])
            state["idx"], state["replica"], new_ref = self._issue(
                args, kwargs, exclude=dead)
            return new_ref

        if self._stream:
            return DeploymentResponseGenerator(ref, on_done=_done,
                                               retry=_retry)
        return DeploymentResponse(ref, on_done=_done, retry=_retry)

    def _remote_compiled(self, args, kwargs):
        """Route one request through the picked replica's compiled DAG.
        ``max_in_flight`` admission doubles as the per-replica ongoing-
        request bound; a broken pipeline falls back to a normal routed
        call (and reports the death so the controller reconciles).
        Returns None when this request cannot ride the compiled plane
        (payload exceeds the ring slot) — the caller then takes the
        ordinary actor-call path."""
        from ray_tpu.dag import DAGBackpressureError, DAGExecutionError
        from ray_tpu.experimental.channel import ChannelFullError

        # TTL'd refresh: steady state pays ZERO controller round trips
        # per request (the whole point of the compiled plane)
        if (self._version == -1 or not self._replicas
                or time.monotonic() - self._refresh_ts
                > _COMPILED_REFRESH_TTL_S):
            self._refresh()
        idx = self._pick_replica()
        replica = self._replicas[idx]
        key = replica._actor_id.binary()
        dag = self._dag_for(idx)
        try:
            fut = dag.execute((self._method, args, kwargs), timeout=60.0)
        except DAGBackpressureError:
            # saturated-but-HEALTHY pipeline: overload must surface to
            # the caller, never read as a replica death (tearing down a
            # live pipeline would error every in-flight request)
            raise
        except ChannelFullError:
            # payload larger than the ring slot: this request rides the
            # ordinary path (object store has no such bound)
            return None
        except DAGExecutionError as e:
            # pipeline already broken/torn down at admission: hand back a
            # response whose result() runs the re-route lazily —
            # remote() itself stays non-blocking
            fut = _BrokenFuture(dag, e)
        self._delta[idx] = self._delta.get(idx, 0) + 1

        def _done():
            self._delta[idx] = self._delta.get(idx, 0) - 1
            self._report_metrics()

        def _fallback():
            return self._compiled_fallback(key, replica, args, kwargs)

        return CompiledDeploymentResponse(fut, on_done=_done,
                                          fallback=_fallback)

    def _compiled_fallback(self, key: bytes, replica, args, kwargs):
        """The routed replica's pipeline broke (replica death): drop its
        DAG, report the death, and run this request once through the
        ordinary actor-call path on a live replica."""
        import ray_tpu

        with _dag_lock():
            ent = self._dags.pop(key, None)
        if ent is not None:
            try:
                ent[1].teardown(timeout=2.0)
            except Exception:
                pass
        self._replica_died(replica)
        idx, _rep, ref = self._issue(args, kwargs, exclude=key)
        try:
            return ray_tpu.get(ref, timeout=60)
        finally:
            self._delta[idx] = self._delta.get(idx, 0) - 1

    def _report_metrics(self):
        try:
            ctrl = self._get_controller()
            total = float(sum(self._load_view()))
            ctrl.record_request_metrics.remote(self.deployment_name, total)
        except Exception:
            pass

    def __getattr__(self, name: str) -> "DeploymentHandle":
        if name.startswith("_"):
            raise AttributeError(name)
        return self.options(method_name=name)

    def __reduce__(self):
        return (DeploymentHandle,
                (self.deployment_name, None, self._method, self._stream))
