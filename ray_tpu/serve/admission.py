"""SLO-gated admission control for the LLM serving tier.

Role analog: the reference has no admission layer (Serve sheds only via
``max_ongoing_requests`` backpressure); production LLM serving needs one
because decode is a shared resource — one over-admitted prompt inflates
EVERY in-flight stream's time-per-output-token. The controller projects
a new request's time-to-first-token from the engine's measured step
latency and the work already queued ahead of it; a request whose
projection breaches the declared SLO (or its own deadline) is SHED at
submission — a fast, honest 503 instead of a slow timeout — and the
decision is observable (``rtpu_serve_admission_sheds_total`` by reason).

The TTFT/TPOT reservoirs double as the latency-percentile surface the
replay load generator and the ``/metrics`` histograms report.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Dict, Optional


class RequestShedError(RuntimeError):
    """Raised at submission when projected latency breaches the SLO (the
    serving tier's 503). Carries ``reason`` for shed-rate accounting and
    a machine-readable ``error_type`` that survives ``TaskError``
    wrapping across process boundaries (ISSUE 13 satellite)."""

    error_type = "shed"

    def __init__(self, msg: str, reason: str = "slo"):
        super().__init__(msg)
        self.reason = reason

    def __reduce__(self):  # keep .reason across process boundaries
        return (RequestShedError, (self.args[0], self.reason))


class DeadlineExceededError(TimeoutError):
    """A request's own ``deadline_s`` elapsed — in the admission queue,
    waiting for its first token, or mid-stream."""

    error_type = "deadline"


@dataclass
class SLOConfig:
    """Declared service-level objectives for one LLM deployment.

    ``None`` disables a gate. ``ttft_s``: shed when projected
    time-to-first-token exceeds this. ``tpot_s``: target time per output
    token; new work is shed while the engine's measured decode step is
    slower than this (admitting more would push every live stream
    further over). ``max_queue_s``: bound on projected admission-queue
    wait alone. ``headroom``: projection safety factor (>1 sheds
    earlier)."""

    ttft_s: Optional[float] = None
    tpot_s: Optional[float] = None
    max_queue_s: Optional[float] = None
    headroom: float = 1.0


def _percentile(sorted_vals, q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(math.ceil(q * len(sorted_vals))) - 1)
    return sorted_vals[max(idx, 0)]


class AdmissionController:
    """Latency bookkeeping + shed decisions for one engine.

    Thread-safe: ``submit`` (caller threads) consults it while the decode
    loop feeds observations. All state is scalar EWMAs and bounded
    reservoirs — a decision is a handful of float ops, never a scan of
    per-request history.
    """

    def __init__(self, slo: Optional[SLOConfig] = None,
                 reservoir: int = 1024):
        self.slo = slo or SLOConfig()
        self._lock = threading.Lock()
        self._step_ewma: Optional[float] = None  # seconds per engine step
        self._ttft = deque(maxlen=reservoir)
        self._tpot = deque(maxlen=reservoir)
        self.sheds: Dict[str, int] = {}
        self.admitted = 0

    # -- observations (decode-loop thread) ---------------------------------

    def observe_step(self, dt_s: float) -> None:
        with self._lock:
            self._step_ewma = (dt_s if self._step_ewma is None
                               else 0.8 * self._step_ewma + 0.2 * dt_s)

    def observe_ttft(self, t_s: float) -> None:
        with self._lock:
            self._ttft.append(t_s)

    def observe_tpot(self, t_s: float) -> None:
        with self._lock:
            self._tpot.append(t_s)

    @property
    def step_s(self) -> float:
        """Current step-latency estimate (0 before the first step — an
        idle engine projects optimistically and lets measurement correct
        it; a cold engine must not shed its warm-up traffic)."""
        return self._step_ewma or 0.0

    # -- projection + decision (submit threads) ----------------------------

    def project_ttft(self, prompt_tokens: int, queued_requests: int,
                     queued_prompt_tokens: int, prefill_chunk: int,
                     free_slots: int) -> float:
        """Projected TTFT for a request joining NOW: the queue ahead must
        drain through the free slots, then its own prompt prefills in
        ``prefill_chunk``-token steps. Deliberately first-order — the SLO
        gate needs the right ORDER of magnitude fast, and headroom plus
        the EWMA absorb the modelling error."""
        step = self.step_s
        chunk = max(prefill_chunk, 1)
        own_steps = math.ceil(max(prompt_tokens, 1) / chunk)
        queue_steps = (math.ceil(queued_prompt_tokens / chunk)
                       + queued_requests) / max(free_slots, 1)
        return step * (own_steps + queue_steps) * self.slo.headroom

    def check_admit(self, prompt_tokens: int, queued_requests: int,
                    queued_prompt_tokens: int, prefill_chunk: int,
                    free_slots: int, active_slots: int,
                    deadline_s: Optional[float] = None) -> None:
        """Raise :class:`RequestShedError` when this request should not
        even join the queue; return silently to admit/queue it."""
        slo = self.slo
        projected = self.project_ttft(prompt_tokens, queued_requests,
                                      queued_prompt_tokens, prefill_chunk,
                                      free_slots)
        if slo.max_queue_s is not None:
            queue_wait = self.step_s * queued_requests * slo.headroom
            if queue_wait > slo.max_queue_s:
                self._shed("queue", f"projected queue wait "
                           f"{queue_wait:.3f}s > max_queue_s "
                           f"{slo.max_queue_s:.3f}s")
        if slo.ttft_s is not None and projected > slo.ttft_s:
            self._shed("ttft", f"projected TTFT {projected:.3f}s > "
                       f"ttft_s {slo.ttft_s:.3f}s")
        if (slo.tpot_s is not None and active_slots > 0
                and self.step_s > slo.tpot_s):
            self._shed("tpot", f"decode step {self.step_s:.3f}s already "
                       f"over tpot_s {slo.tpot_s:.3f}s")
        if deadline_s is not None and projected > deadline_s:
            self._shed("deadline", f"projected TTFT {projected:.3f}s > "
                       f"request deadline {deadline_s:.3f}s")
        with self._lock:
            self.admitted += 1

    def _shed(self, reason: str, msg: str):
        with self._lock:
            self.sheds[reason] = self.sheds.get(reason, 0) + 1
        raise RequestShedError(f"request shed ({reason}): {msg}",
                               reason=reason)

    # -- reporting ---------------------------------------------------------

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            ttft = sorted(self._ttft)
            tpot = sorted(self._tpot)
            sheds = dict(self.sheds)
            admitted = self.admitted
        return {
            "step_ewma_s": self.step_s,
            "ttft_p50_s": _percentile(ttft, 0.50),
            "ttft_p99_s": _percentile(ttft, 0.99),
            "tpot_p50_s": _percentile(tpot, 0.50),
            "tpot_p99_s": _percentile(tpot, 0.99),
            "admitted": admitted,
            "shed": sum(sheds.values()),
            "shed_by_reason": sheds,
        }
