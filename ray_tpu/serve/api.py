"""serve.run / serve.shutdown / handles / multiplexing.

Role analog: ``python/ray/serve/api.py`` (``serve.run :545``). The client
side: package the bound application into specs, hand them to the named
controller actor, return the entry deployment's handle.
"""

from __future__ import annotations

import contextvars
import functools
from collections import OrderedDict
from typing import Any, Callable, Dict, Optional

import cloudpickle

import ray_tpu
from ray_tpu.serve.controller import CONTROLLER_NAME, ServeController
from ray_tpu.serve.deployment import Application, Deployment
from ray_tpu.serve.handle import DeploymentHandle, _AppRefSentinel


def _get_or_create_controller():
    try:
        return ray_tpu.get_actor(CONTROLLER_NAME)
    except Exception:
        cls = ray_tpu.remote(ServeController)
        return cls.options(name=CONTROLLER_NAME, num_cpus=0).remote()


def _spec_for(app: Application) -> Dict[str, Any]:
    dep = app.deployment
    composed = []

    def encode(x):
        if isinstance(x, Application):
            composed.append(x.deployment.name)
            return _AppRefSentinel(x.deployment.name)
        return x

    init_args = tuple(encode(a) for a in app.init_args)
    init_kwargs = {k: encode(v) for k, v in app.init_kwargs.items()}
    cfg = dep.config
    return {
        "name": dep.name,
        "cls_blob": cloudpickle.dumps(dep.func_or_class),
        "init_args": cloudpickle.dumps(init_args),
        "init_kwargs": cloudpickle.dumps(init_kwargs),
        "composed": composed,
        "config": {
            "num_replicas": cfg.num_replicas,
            "max_ongoing_requests": cfg.max_ongoing_requests,
            "autoscaling_config": (vars(cfg.autoscaling_config)
                                   if cfg.autoscaling_config else None),
            "ray_actor_options": cfg.ray_actor_options,
            "user_config": cfg.user_config,
            "compiled": bool(getattr(cfg, "compiled", False)),
        },
    }


# app name -> (route, entry handle): feeds start_http_proxy / the CLI
_deployed_apps: Dict[str, tuple] = {}


def run(app: Application, *, name: str = "default",
        route_prefix: Optional[str] = None) -> DeploymentHandle:
    """Deploy the application; returns a handle to its entry deployment."""
    if isinstance(app, Deployment):
        app = app.bind()
    controller = _get_or_create_controller()
    specs = [_spec_for(a) for a in app.flatten().values()]
    ray_tpu.get(controller.deploy_application.remote(specs))
    handle = DeploymentHandle(app.deployment.name, controller)
    handle._refresh(force=True)
    route = (route_prefix or app.deployment.name).strip("/")
    _deployed_apps[name] = (route, handle)
    return handle


def start_http_proxy(port: int = 8000, host: str = "127.0.0.1"):
    """Start the HTTP proxy with every deployed application's route
    registered (reference: per-node ProxyActor wiring routes from the
    controller's long-poll; here routes come from this process's deploys)."""
    from ray_tpu.serve.proxy import HTTPProxy

    proxy = HTTPProxy(host=host, port=port)
    for route, handle in _deployed_apps.values():
        proxy.register(route, handle)
    proxy.start()
    return proxy


def get_deployment_handle(deployment_name: str,
                          app_name: str = "default") -> DeploymentHandle:
    return DeploymentHandle(deployment_name, _get_or_create_controller())


def delete(name: str) -> None:
    controller = _get_or_create_controller()
    ray_tpu.get(controller.delete_deployment.remote(name))
    # prune proxy-route entries whose entry deployment just went away
    for app, (_route, handle) in list(_deployed_apps.items()):
        if handle.deployment_name == name:
            _deployed_apps.pop(app, None)


def status() -> Dict[str, Any]:
    controller = _get_or_create_controller()
    return ray_tpu.get(controller.list_deployments.remote())


def model_report() -> Dict[str, Any]:
    """Cluster-wide multi-model residency view (``rtpu list models`` /
    ``GET /api/models``). Read-only: never creates a controller."""
    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
    except Exception:
        return {}
    return ray_tpu.get(controller.model_report.remote())


def shutdown() -> None:
    _deployed_apps.clear()  # stale handles must not outlive the controller
    # compiled execution plane: tear down every cached per-replica DAG
    # while the replicas are still alive (graceful _Stop propagation) —
    # their shm channels must not outlive serve
    from ray_tpu.serve import handle as _handle_mod

    with _handle_mod._dag_lock():
        dags = [ent[1] for ent in _handle_mod._dag_cache.values()]
        _handle_mod._dag_cache.clear()
    for dag in dags:
        try:
            dag.teardown(timeout=2.0)
        except Exception:
            pass
    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
    except Exception:
        return
    try:
        ray_tpu.get(controller.shutdown.remote())
        ray_tpu.kill(controller)
    except Exception:
        pass


# ---------------------------------------------------------------------------
# Model multiplexing (reference serve/multiplex.py)
# ---------------------------------------------------------------------------

_multiplexed_model_id: contextvars.ContextVar = contextvars.ContextVar(
    "multiplexed_model_id", default="")


def get_multiplexed_model_id() -> str:
    return _multiplexed_model_id.get()


def multiplexed(fn=None, *, max_num_models_per_replica: int = 3):
    """Decorate an async model-loader method; an LRU of loaded models is
    kept per replica (reference ``serve/multiplex.py``)."""

    def wrap(load_fn):
        caches: Dict[int, OrderedDict] = {}

        @functools.wraps(load_fn)
        async def wrapper(self, model_id: str):
            cache = caches.setdefault(id(self), OrderedDict())
            if model_id in cache:
                cache.move_to_end(model_id)
                return cache[model_id]
            model = load_fn(self, model_id)
            import inspect

            if inspect.iscoroutine(model):
                model = await model
            cache[model_id] = model
            if len(cache) > max_num_models_per_replica:
                cache.popitem(last=False)
            return model

        return wrapper

    if fn is None:
        return wrap
    return wrap(fn)
