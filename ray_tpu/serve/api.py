"""serve.run / serve.shutdown / handles / multiplexing.

Role analog: ``python/ray/serve/api.py`` (``serve.run :545``). The client
side: package the bound application into specs, hand them to the named
controller actor, return the entry deployment's handle.
"""

from __future__ import annotations

import contextvars
import functools
from collections import OrderedDict
from typing import Any, Callable, Dict, Optional

import cloudpickle

import ray_tpu
from ray_tpu.serve.controller import CONTROLLER_NAME, ServeController
from ray_tpu.serve.deployment import Application, Deployment
from ray_tpu.serve.handle import DeploymentHandle, _AppRefSentinel


def _get_or_create_controller():
    try:
        return ray_tpu.get_actor(CONTROLLER_NAME)
    except Exception:
        cls = ray_tpu.remote(ServeController)
        return cls.options(name=CONTROLLER_NAME, num_cpus=0).remote()


def _spec_for(app: Application) -> Dict[str, Any]:
    dep = app.deployment
    composed = []

    def encode(x):
        if isinstance(x, Application):
            composed.append(x.deployment.name)
            return _AppRefSentinel(x.deployment.name)
        return x

    init_args = tuple(encode(a) for a in app.init_args)
    init_kwargs = {k: encode(v) for k, v in app.init_kwargs.items()}
    cfg = dep.config
    return {
        "name": dep.name,
        "cls_blob": cloudpickle.dumps(dep.func_or_class),
        "init_args": cloudpickle.dumps(init_args),
        "init_kwargs": cloudpickle.dumps(init_kwargs),
        "composed": composed,
        "config": {
            "num_replicas": cfg.num_replicas,
            "max_ongoing_requests": cfg.max_ongoing_requests,
            "autoscaling_config": (vars(cfg.autoscaling_config)
                                   if cfg.autoscaling_config else None),
            "ray_actor_options": cfg.ray_actor_options,
            "user_config": cfg.user_config,
        },
    }


def run(app: Application, *, name: str = "default",
        route_prefix: Optional[str] = None) -> DeploymentHandle:
    """Deploy the application; returns a handle to its entry deployment."""
    if isinstance(app, Deployment):
        app = app.bind()
    controller = _get_or_create_controller()
    specs = [_spec_for(a) for a in app.flatten().values()]
    ray_tpu.get(controller.deploy_application.remote(specs))
    handle = DeploymentHandle(app.deployment.name, controller)
    handle._refresh(force=True)
    return handle


def get_deployment_handle(deployment_name: str,
                          app_name: str = "default") -> DeploymentHandle:
    return DeploymentHandle(deployment_name, _get_or_create_controller())


def delete(name: str) -> None:
    controller = _get_or_create_controller()
    ray_tpu.get(controller.delete_deployment.remote(name))


def status() -> Dict[str, Any]:
    controller = _get_or_create_controller()
    return ray_tpu.get(controller.list_deployments.remote())


def shutdown() -> None:
    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
    except Exception:
        return
    try:
        ray_tpu.get(controller.shutdown.remote())
        ray_tpu.kill(controller)
    except Exception:
        pass


# ---------------------------------------------------------------------------
# Model multiplexing (reference serve/multiplex.py)
# ---------------------------------------------------------------------------

_multiplexed_model_id: contextvars.ContextVar = contextvars.ContextVar(
    "multiplexed_model_id", default="")


def get_multiplexed_model_id() -> str:
    return _multiplexed_model_id.get()


def multiplexed(fn=None, *, max_num_models_per_replica: int = 3):
    """Decorate an async model-loader method; an LRU of loaded models is
    kept per replica (reference ``serve/multiplex.py``)."""

    def wrap(load_fn):
        caches: Dict[int, OrderedDict] = {}

        @functools.wraps(load_fn)
        async def wrapper(self, model_id: str):
            cache = caches.setdefault(id(self), OrderedDict())
            if model_id in cache:
                cache.move_to_end(model_id)
                return cache[model_id]
            model = load_fn(self, model_id)
            import inspect

            if inspect.iscoroutine(model):
                model = await model
            cache[model_id] = model
            if len(cache) > max_num_models_per_replica:
                cache.popitem(last=False)
            return model

        return wrapper

    if fn is None:
        return wrap
    return wrap(fn)
