"""ServeController: deployment state reconciliation + autoscaling.

Role analog: ``python/ray/serve/_private/controller.py:86`` with the
``DeploymentStateManager`` reconciler (``deployment_state.py:1226``) and
autoscaling (``autoscaling_state.py``). The controller is a named actor;
``deploy``/``delete`` reconcile replica actors synchronously (create the
missing, kill the surplus), and ``autoscale_tick`` applies the queue-based
policy from metrics the handles report. Config updates broadcast by bumping
a routing-table version handles poll (the LongPollHost analog, pull-based).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

CONTROLLER_NAME = "SERVE_CONTROLLER"


class ServeController:
    def __init__(self):
        # name -> {"app": Application-ish dict, "replicas": [handles], ...}
        self._deployments: Dict[str, Dict[str, Any]] = {}
        self._version = 0
        # deployment -> list of (timestamp, ongoing) samples from handles
        self._metrics: Dict[str, List[Any]] = {}

    # -- deploy / delete --------------------------------------------------

    def deploy_application(self, specs: List[Dict[str, Any]]) -> int:
        """specs: one dict per deployment: {name, cls_blob, init_args,
        init_kwargs, config(dict), composed(list of dep names)}."""
        import cloudpickle

        for spec in specs:
            name = spec["name"]
            entry = self._deployments.get(name)
            if entry is None:
                entry = {"replicas": [], "spec": spec}
                self._deployments[name] = entry
            else:
                entry["spec"] = spec
            entry["target"] = spec["config"]["num_replicas"]
        # resolve composition: build handles for dependencies first
        order = self._topo_order(specs)
        for name in order:
            self._reconcile(name)
        self._version += 1
        return self._version

    def delete_deployment(self, name: str) -> None:
        entry = self._deployments.pop(name, None)
        if entry:
            for r in entry["replicas"]:
                self._kill(r)
        self._version += 1

    def shutdown(self) -> None:
        for name in list(self._deployments):
            self.delete_deployment(name)

    def _topo_order(self, specs) -> List[str]:
        by_name = {s["name"]: s for s in specs}
        seen: List[str] = []

        def visit(n):
            if n in seen or n not in by_name:
                return
            for dep in by_name[n].get("composed", []):
                visit(dep)
            seen.append(n)

        for s in specs:
            visit(s["name"])
        return seen

    # -- reconciliation ---------------------------------------------------

    def _make_replica(self, spec: Dict[str, Any]):
        import cloudpickle

        import ray_tpu
        from ray_tpu.serve.replica import ReplicaActor

        cls_or_fn = cloudpickle.loads(spec["cls_blob"])
        init_args = cloudpickle.loads(spec["init_args"])
        init_kwargs = cloudpickle.loads(spec["init_kwargs"])
        # composed deps: replace sentinels with live handles
        from ray_tpu.serve.handle import DeploymentHandle, _AppRefSentinel

        def resolve(x):
            if isinstance(x, _AppRefSentinel):
                return DeploymentHandle(x.name, controller=None)
            return x

        init_args = tuple(resolve(a) for a in init_args)
        init_kwargs = {k: resolve(v) for k, v in init_kwargs.items()}
        opts = dict(spec["config"].get("ray_actor_options") or {})
        if spec["config"].get("compiled"):
            # compiled execution plane: the DAG exec loop occupies one
            # concurrency slot for the deployment's lifetime — keep a
            # second so health checks / reconfigure stay reachable
            opts.setdefault("max_concurrency", 2)
        actor_cls = ray_tpu.remote(ReplicaActor)
        return actor_cls.options(**opts).remote(
            cls_or_fn, init_args, init_kwargs,
            spec["config"].get("user_config"))

    def _reconcile(self, name: str) -> None:
        entry = self._deployments.get(name)
        if not entry:
            return
        target = entry.get("target", 1)
        replicas = entry["replicas"]
        while len(replicas) < target:
            replicas.append(self._make_replica(entry["spec"]))
        while len(replicas) > target:
            self._kill(replicas.pop())

    def report_replica_death(self, name: str, actor_id: bytes) -> int:
        """Router-reported replica death (the reference's health-check /
        unhealthy-replica path, pull-free: handles observe ActorDiedError
        on the request they routed). Drop the dead replica, reconcile a
        replacement up to the target count, and bump the version so every
        handle refreshes its routing table."""
        entry = self._deployments.get(name)
        if entry is None:
            return self._version
        before = len(entry["replicas"])
        entry["replicas"] = [r for r in entry["replicas"]
                             if r._actor_id.binary() != actor_id]
        if len(entry["replicas"]) != before:
            self._reconcile(name)
            self._version += 1
        return self._version

    def _kill(self, replica) -> None:
        import ray_tpu

        try:
            ray_tpu.kill(replica)
        except Exception:
            pass

    # -- routing table ----------------------------------------------------

    def get_routing_info(self, name: str):
        entry = self._deployments.get(name)
        if entry is None:
            return None
        return {
            "version": self._version,
            "replicas": list(entry["replicas"]),
            "max_ongoing_requests":
                entry["spec"]["config"].get("max_ongoing_requests", 8),
            "compiled": bool(entry["spec"]["config"].get("compiled")),
        }

    def get_version(self) -> int:
        return self._version

    def list_deployments(self) -> Dict[str, Dict[str, Any]]:
        return {
            name: {
                "num_replicas": len(e["replicas"]),
                "target": e.get("target"),
            }
            for name, e in self._deployments.items()
        }

    # -- autoscaling ------------------------------------------------------

    def record_request_metrics(self, name: str, ongoing: float) -> None:
        self._metrics.setdefault(name, []).append((time.time(), ongoing))
        # keep the last minute
        cutoff = time.time() - 60.0
        self._metrics[name] = [(t, o) for t, o in self._metrics[name]
                               if t >= cutoff]

    def autoscale_tick(self) -> Dict[str, int]:
        """Apply the autoscaling policy (reference
        ``autoscaling_policy.py``: scale to ongoing/target ratio, clamped)."""
        decisions = {}
        for name, entry in self._deployments.items():
            cfg = entry["spec"]["config"].get("autoscaling_config")
            if not cfg:
                continue
            samples = [o for _, o in self._metrics.get(name, [])]
            if not samples:
                continue
            avg_ongoing = sum(samples) / len(samples)
            cur = max(len(entry["replicas"]), 1)
            desired = avg_ongoing / max(cfg["target_ongoing_requests"], 1e-9)
            import math

            new = cur
            if desired > cur:
                new = min(int(math.ceil(desired)), cfg["max_replicas"])
            elif desired < cur * cfg["downscale_factor"]:
                new = max(int(math.ceil(desired)), cfg["min_replicas"])
            if new != cur:
                entry["target"] = new
                self._reconcile(name)
                self._version += 1
                decisions[name] = new
        return decisions
