"""ServeController: deployment state reconciliation + autoscaling.

Role analog: ``python/ray/serve/_private/controller.py:86`` with the
``DeploymentStateManager`` reconciler (``deployment_state.py:1226``) and
autoscaling (``autoscaling_state.py``). The controller is a named actor;
``deploy``/``delete`` reconcile replica actors synchronously (create the
missing, kill the surplus), and ``autoscale_tick`` applies the queue-based
policy from metrics the handles report. Config updates broadcast by bumping
a routing-table version handles poll (the LongPollHost analog, pull-based).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

CONTROLLER_NAME = "SERVE_CONTROLLER"


class ServeController:
    def __init__(self):
        # name -> {"app": Application-ish dict, "replicas": [handles], ...}
        self._deployments: Dict[str, Dict[str, Any]] = {}
        self._version = 0
        # deployment -> list of (timestamp, ongoing) samples from handles
        self._metrics: Dict[str, List[Any]] = {}

    # -- deploy / delete --------------------------------------------------

    def deploy_application(self, specs: List[Dict[str, Any]]) -> int:
        """specs: one dict per deployment: {name, cls_blob, init_args,
        init_kwargs, config(dict), composed(list of dep names)}."""
        import cloudpickle

        for spec in specs:
            name = spec["name"]
            entry = self._deployments.get(name)
            if entry is None:
                entry = {"replicas": [], "spec": spec}
                self._deployments[name] = entry
            else:
                entry["spec"] = spec
            entry["target"] = spec["config"]["num_replicas"]
        # resolve composition: build handles for dependencies first
        order = self._topo_order(specs)
        for name in order:
            self._reconcile(name)
        self._version += 1
        return self._version

    def delete_deployment(self, name: str) -> None:
        entry = self._deployments.pop(name, None)
        if entry:
            for r in entry["replicas"]:
                self._kill(r)
        self._version += 1

    def shutdown(self) -> None:
        for name in list(self._deployments):
            self.delete_deployment(name)

    def _topo_order(self, specs) -> List[str]:
        by_name = {s["name"]: s for s in specs}
        seen: List[str] = []

        def visit(n):
            if n in seen or n not in by_name:
                return
            for dep in by_name[n].get("composed", []):
                visit(dep)
            seen.append(n)

        for s in specs:
            visit(s["name"])
        return seen

    # -- reconciliation ---------------------------------------------------

    def _make_replica(self, spec: Dict[str, Any]):
        import cloudpickle

        import ray_tpu
        from ray_tpu.serve.replica import ReplicaActor

        cls_or_fn = cloudpickle.loads(spec["cls_blob"])
        init_args = cloudpickle.loads(spec["init_args"])
        init_kwargs = cloudpickle.loads(spec["init_kwargs"])
        # composed deps: replace sentinels with live handles
        from ray_tpu.serve.handle import DeploymentHandle, _AppRefSentinel

        def resolve(x):
            if isinstance(x, _AppRefSentinel):
                return DeploymentHandle(x.name, controller=None)
            return x

        init_args = tuple(resolve(a) for a in init_args)
        init_kwargs = {k: resolve(v) for k, v in init_kwargs.items()}
        opts = dict(spec["config"].get("ray_actor_options") or {})
        if spec["config"].get("compiled"):
            # compiled execution plane: the DAG exec loop occupies one
            # concurrency slot for the deployment's lifetime — keep a
            # second so health checks / reconfigure stay reachable
            opts.setdefault("max_concurrency", 2)
        actor_cls = ray_tpu.remote(ReplicaActor)
        return actor_cls.options(**opts).remote(
            cls_or_fn, init_args, init_kwargs,
            spec["config"].get("user_config"),
            deployment_name=spec["name"])

    def _reconcile(self, name: str) -> None:
        entry = self._deployments.get(name)
        if not entry:
            return
        target = entry.get("target", 1)
        replicas = entry["replicas"]
        while len(replicas) < target:
            replicas.append(self._make_replica(entry["spec"]))
        while len(replicas) > target:
            victim = replicas.pop()
            # drop its load report with it: a scaled-down replica's last
            # (typically high-occupancy) report must not keep inflating
            # the autoscaler's average for 30 more seconds
            entry.get("loads", {}).pop(victim._actor_id.binary(), None)
            self._kill(victim)

    def report_replica_death(self, name: str, actor_id: bytes) -> int:
        """Router-reported replica death (the reference's health-check /
        unhealthy-replica path, pull-free: handles observe ActorDiedError
        on the request they routed). Drop the dead replica, reconcile a
        replacement up to the target count, and bump the version so every
        handle refreshes its routing table."""
        entry = self._deployments.get(name)
        if entry is None:
            return self._version
        before = len(entry["replicas"])
        entry["replicas"] = [r for r in entry["replicas"]
                             if r._actor_id.binary() != actor_id]
        entry.get("loads", {}).pop(actor_id, None)
        if len(entry["replicas"]) != before:
            self._reconcile(name)
            self._version += 1
            # lifecycle events (controller runs inside an actor, so
            # these ride the worker's pipe push like any other event)
            try:
                from ray_tpu.util import events

                events.emit("serve_replica_death", deployment=name,
                            actor_id=actor_id.hex(),
                            replicas_left=len(entry["replicas"]))
                events.emit("serve_reroute", deployment=name,
                            version=self._version,
                            target=entry.get("target", 1))
            except Exception:
                pass
        return self._version

    def _kill(self, replica) -> None:
        import ray_tpu

        try:
            ray_tpu.kill(replica)
        except Exception:
            pass

    # -- routing table ----------------------------------------------------

    def get_routing_info(self, name: str):
        entry = self._deployments.get(name)
        if entry is None:
            return None
        return {
            "version": self._version,
            "replicas": list(entry["replicas"]),
            "max_ongoing_requests":
                entry["spec"]["config"].get("max_ongoing_requests", 8),
            "compiled": bool(entry["spec"]["config"].get("compiled")),
            "has_loads": bool(entry.get("loads")),
        }

    def get_version(self) -> int:
        return self._version

    def list_deployments(self) -> Dict[str, Dict[str, Any]]:
        return {
            name: {
                "num_replicas": len(e["replicas"]),
                "target": e.get("target"),
            }
            for name, e in self._deployments.items()
        }

    # -- replica load reports (KV-aware routing + autoscaling) ------------

    def report_replica_load(self, name: str, actor_id: bytes,
                            load: Dict[str, Any]) -> None:
        """Replica-pushed load state ({inflight, kv_free, kv_total} from
        the deployment's ``load_state()``): the routing signal handles
        fold into their pick score, and the KV-occupancy input to
        autoscaling. Stamped on arrival so readers can age it out."""
        entry = self._deployments.get(name)
        if entry is None:
            return
        rec = dict(load)
        rec["ts"] = time.time()
        first = not entry.get("loads")
        entry.setdefault("loads", {})[actor_id] = rec
        if first:
            # first report flips the deployment's has_loads bit in the
            # routing info — bump the version so handles refetch it and
            # start consulting the KV view (handles of deployments that
            # never report skip the controller probe entirely)
            self._version += 1

    def get_replica_loads(self, name: str) -> Dict[bytes, Dict[str, Any]]:
        entry = self._deployments.get(name)
        if entry is None:
            return {}
        return dict(entry.get("loads", {}))

    def model_report(self) -> Dict[str, Any]:
        """Cluster-wide multi-model view (``rtpu list models`` /
        ``/api/models``): per deployment, each replica's resident
        models (with residency tier + swap counters from the registry)
        and its published prefix-digest summary — assembled from the
        SAME load reports routing runs on, so what this returns is
        exactly what handles see."""
        out: Dict[str, Any] = {}
        for name, entry in self._deployments.items():
            reps = {}
            for actor_id, rec in (entry.get("loads") or {}).items():
                if "models" not in rec:
                    continue
                reps[actor_id.hex()] = {
                    "models": rec.get("models", {}),
                    "prefix_digest": rec.get("prefix_digest", []),
                    "inflight": rec.get("inflight", 0),
                    "ts": rec.get("ts", 0.0),
                }
            if reps:
                out[name] = {"replicas": reps}
        return out

    # -- autoscaling ------------------------------------------------------

    def record_request_metrics(self, name: str, ongoing: float) -> None:
        self._metrics.setdefault(name, []).append((time.time(), ongoing))
        # keep the last minute
        cutoff = time.time() - 60.0
        self._metrics[name] = [(t, o) for t, o in self._metrics[name]
                               if t >= cutoff]

    def _desired_replicas(self, name: str) -> Optional[int]:
        """Autoscaling policy (reference ``autoscaling_policy.py`` plus a
        KV-pressure input): desired = max over the ongoing/target ratio
        and the KV-occupancy/target ratio — an LLM deployment can be
        KV-bound long before its request queue looks deep (one long
        context pins blocks for its whole stream)."""
        entry = self._deployments.get(name)
        if entry is None:
            return None
        cfg = entry["spec"]["config"].get("autoscaling_config")
        if not cfg:
            return None
        cur = max(len(entry["replicas"]), 1)
        desired = None
        samples = [o for _, o in self._metrics.get(name, [])]
        if samples:
            avg_ongoing = sum(samples) / len(samples)
            desired = avg_ongoing / max(cfg["target_ongoing_requests"],
                                        1e-9)
        target_kv = cfg.get("target_kv_utilization")
        if target_kv:
            cutoff = time.time() - 30.0
            # .get defaults throughout: load reports are whatever a user
            # deployment's load_state() returned — a missing key must
            # not fail every deployment's autoscale tick
            fracs = [1.0 - l.get("kv_free", 0) / l["kv_total"]
                     for l in entry.get("loads", {}).values()
                     if l.get("ts", 0) >= cutoff and l.get("kv_total")]
            if fracs:
                kv_desired = cur * (sum(fracs) / len(fracs)) / target_kv
                desired = max(desired or 0.0, kv_desired)
        if desired is None:
            return None
        import math

        new = cur
        if desired > cur:
            new = min(int(math.ceil(desired)), cfg["max_replicas"])
        elif desired < cur * cfg["downscale_factor"]:
            new = max(int(math.ceil(desired)), cfg["min_replicas"])
        return new

    def autoscale_tick(self) -> Dict[str, int]:
        """Apply the autoscaling policy (ongoing/target ratio plus KV
        occupancy, clamped)."""
        decisions = {}
        for name, entry in self._deployments.items():
            new = self._desired_replicas(name)
            if new is None or new == len(entry["replicas"]):
                continue
            entry["target"] = new
            self._reconcile(name)
            self._version += 1
            decisions[name] = new
        return decisions

    def v2_demand(self) -> List[Dict[str, float]]:
        """Pending replica demand as resource bundles — the bridge into
        autoscaler v2: feed this as (or into) the AutoscalerV2
        ``load_source`` so serve scale-up requests become node launches
        when the cluster itself is out of capacity."""
        bundles: List[Dict[str, float]] = []
        for name, entry in self._deployments.items():
            new = self._desired_replicas(name)
            if new is None:
                continue
            short = new - len(entry["replicas"])
            if short <= 0:
                continue
            opts = entry["spec"]["config"].get("ray_actor_options") or {}
            # unset num_cpus defaults to 1; an EXPLICIT 0 (the LLM
            # deployments here) must not advertise phantom CPU demand
            cpu = opts.get("num_cpus")
            cpu = 1.0 if cpu is None else float(cpu)
            bundle = {"CPU": cpu} if cpu > 0 else {}
            for k, v in (opts.get("resources") or {}).items():
                bundle[k] = float(v)
            bundles.extend(dict(bundle) for _ in range(short))
        return bundles
