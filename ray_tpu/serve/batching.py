"""@serve.batch — dynamic request batching.

Role analog: ``python/ray/serve/batching.py``. Concurrent calls to the
decorated async method are queued; a flush runs the underlying function on
the whole batch when ``max_batch_size`` accumulate or ``batch_wait_timeout_s``
elapses. On a TPU replica this is what keeps the MXU fed: many small
requests become one batched jitted call.
"""

from __future__ import annotations

import asyncio
import functools
from typing import Any, Callable, List, Optional


class _BatchQueue:
    def __init__(self, fn, max_batch_size: int, timeout_s: float):
        self.fn = fn
        self.max_batch_size = max_batch_size
        self.timeout_s = timeout_s
        self.pending: List = []   # list of (arg, future)
        self._flush_task: Optional[asyncio.TimerHandle] = None
        self._lock = asyncio.Lock()

    async def submit(self, arg) -> Any:
        loop = asyncio.get_event_loop()
        fut = loop.create_future()
        async with self._lock:
            self.pending.append((arg, fut))
            if len(self.pending) >= self.max_batch_size:
                await self._flush()
            elif len(self.pending) == 1:
                loop.create_task(self._timer_flush())
        return await fut

    async def _timer_flush(self):
        await asyncio.sleep(self.timeout_s)
        async with self._lock:
            await self._flush()

    async def _flush(self):
        if not self.pending:
            return
        batch, self.pending = self.pending, []
        args = [a for a, _ in batch]
        futs = [f for _, f in batch]
        try:
            results = self.fn(args)
            if asyncio.iscoroutine(results):
                results = await results
            if len(results) != len(args):
                raise ValueError(
                    f"batched fn returned {len(results)} results for "
                    f"{len(args)} inputs")
            for f, r in zip(futs, results):
                if not f.done():
                    f.set_result(r)
        except BaseException as e:  # noqa: BLE001
            for f in futs:
                if not f.done():
                    f.set_exception(e)


def batch(fn=None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01):
    """Decorate ``async def method(self, batch_of_args)`` (or a free async
    fn taking a list) so callers invoke it with single items."""

    def wrap(f):
        queues = {}  # per-instance (or module) queue

        @functools.wraps(f)
        async def wrapper(*args):
            if len(args) == 2:           # bound method: (self, item)
                owner, item = args
                key = id(owner)
                bound = functools.partial(f, owner)
            else:                        # free function: (item,)
                (item,) = args
                key = id(f)
                bound = f
            q = queues.get(key)
            if q is None:
                q = queues[key] = _BatchQueue(bound, max_batch_size,
                                              batch_wait_timeout_s)
            return await q.submit(item)

        wrapper._is_serve_batch = True
        return wrapper

    if fn is None:
        return wrap
    return wrap(fn)
