"""HTTP proxy: minimal asyncio HTTP/1.1 server routing to deployments.

Role analog: ``python/ray/serve/_private/proxy.py:1112`` (``HTTPProxy``
:748). The reference runs uvicorn/ASGI per node; here a stdlib asyncio
server (no external deps) parses requests, routes ``/<deployment>`` to the
deployment's handle, and returns JSON. Runs on a daemon thread in the
driver process (single-node data plane).
"""

from __future__ import annotations

import asyncio
import json
import threading
from typing import Any, Dict, Optional

from ray_tpu.serve.handle import DeploymentHandle


class HTTPProxy:
    def __init__(self, host: str = "127.0.0.1", port: int = 8000):
        self.host = host
        self.port = port
        self._handles: Dict[str, DeploymentHandle] = {}
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._started = threading.Event()

    def register(self, route: str, handle: DeploymentHandle) -> None:
        self._handles[route.strip("/")] = handle

    # -- server -----------------------------------------------------------

    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter):
        try:
            request_line = await reader.readline()
            if not request_line:
                return
            method, path, _ = request_line.decode().split(" ", 2)
            headers: Dict[str, str] = {}
            while True:
                line = await reader.readline()
                if line in (b"\r\n", b"\n", b""):
                    break
                k, _, v = line.decode().partition(":")
                headers[k.strip().lower()] = v.strip()
            body = b""
            n = int(headers.get("content-length", 0))
            if n:
                body = await reader.readexactly(n)
            if "?stream=1" in path or path.endswith("&stream=1"):
                await self._route_streaming(method, path, body, writer)
                return
            status, payload = await self._route(method, path, body)
            data = json.dumps(payload).encode()
            writer.write(
                f"HTTP/1.1 {status}\r\nContent-Type: application/json\r\n"
                f"Content-Length: {len(data)}\r\nConnection: close"
                f"\r\n\r\n".encode() + data)
            await writer.drain()
        except Exception:
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass

    async def _route(self, method: str, path: str, body: bytes):
        name = path.strip("/").split("?")[0].split("/")[0]
        if name == "-" or name == "":
            return "200 OK", {"status": "ok",
                              "routes": sorted(self._handles)}
        handle = self._handles.get(name)
        if handle is None:
            return "404 Not Found", {"error": f"no deployment {name!r}"}
        arg: Any = None
        if body:
            try:
                arg = json.loads(body)
            except json.JSONDecodeError:
                arg = body.decode()
        loop = asyncio.get_event_loop()
        try:
            resp = handle.remote(arg) if arg is not None else handle.remote()
            result = await loop.run_in_executor(None, resp.result)
            return "200 OK", {"result": result}
        except Exception as e:  # noqa: BLE001
            return "500 Internal Server Error", {"error": str(e)}

    async def _route_streaming(self, method: str, path: str, body: bytes,
                               writer: asyncio.StreamWriter):
        """Chunked transfer: one JSON line per yielded item (reference
        HTTPProxy streaming responses, proxy.py:748 role)."""
        name = path.strip("/").split("?")[0].split("/")[0]
        handle = self._handles.get(name)
        if handle is None:
            data = json.dumps({"error": f"no deployment {name!r}"}).encode()
            writer.write(
                f"HTTP/1.1 404 Not Found\r\nContent-Length: {len(data)}"
                f"\r\nConnection: close\r\n\r\n".encode() + data)
            await writer.drain()
            return
        arg: Any = None
        if body:
            try:
                arg = json.loads(body)
            except json.JSONDecodeError:
                arg = body.decode()
        writer.write(
            b"HTTP/1.1 200 OK\r\nContent-Type: application/x-ndjson\r\n"
            b"Transfer-Encoding: chunked\r\nConnection: close\r\n\r\n")
        loop = asyncio.get_event_loop()
        gen = (handle.options(stream=True).remote(arg) if arg is not None
               else handle.options(stream=True).remote())
        it = iter(gen)

        def _next():
            try:
                return True, next(it)
            except StopIteration:
                return False, None

        while True:
            more, item = await loop.run_in_executor(None, _next)
            if not more:
                break
            chunk = (json.dumps({"result": item}) + "\n").encode()
            writer.write(f"{len(chunk):x}\r\n".encode() + chunk + b"\r\n")
            await writer.drain()
        writer.write(b"0\r\n\r\n")
        await writer.drain()

    def _run(self):
        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)

        async def main():
            server = await asyncio.start_server(
                self._handle_conn, self.host, self.port)
            if self.port == 0:
                self.port = server.sockets[0].getsockname()[1]
            self._started.set()
            async with server:
                await server.serve_forever()

        try:
            self._loop.run_until_complete(main())
        except (asyncio.CancelledError, RuntimeError):
            # RuntimeError("Event loop stopped before Future completed."):
            # the expected shape of stop() interrupting serve_forever
            pass

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="serve_http_proxy")
        self._thread.start()
        self._started.wait(timeout=10)

    def stop(self) -> None:
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self._loop.stop)
