"""HTTP proxy: asyncio HTTP/1.1 server routing to deployments.

Role analog: ``python/ray/serve/_private/proxy.py:1112`` (``HTTPProxy``
:748). The reference rides uvicorn/ASGI per node; here a stdlib asyncio
server (no external deps) speaks enough HTTP/1.1 for a real client
matrix — keep-alive, chunked request bodies, 400/404/405/413/500 — and
routes ``/<deployment>`` to the deployment's handle. Runs on a daemon
thread in the driver process (single-node data plane). The gRPC ingress
with the same routing lives in ``grpc_proxy.py``.
"""

from __future__ import annotations

import asyncio
import json
import os
import threading
from typing import Any, Dict, Optional

from ray_tpu import config
from ray_tpu.serve.handle import DeploymentHandle

MAX_BODY = int(config.get("serve_max_body"))
ALLOWED_METHODS = {"GET", "POST", "PUT", "DELETE", "HEAD"}


class _BodyTooLarge(Exception):
    pass


class HTTPProxy:
    def __init__(self, host: str = "127.0.0.1", port: int = 8000):
        self.host = host
        self.port = port
        self._handles: Dict[str, DeploymentHandle] = {}
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._started = threading.Event()
        self._stop_event: Optional[asyncio.Event] = None

    def register(self, route: str, handle: DeploymentHandle) -> None:
        self._handles[route.strip("/")] = handle

    # -- server -----------------------------------------------------------

    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter):
        try:
            while True:
                if not await self._handle_one(reader, writer):
                    break
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        except Exception:
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass

    async def _read_chunked(self, reader: asyncio.StreamReader) -> bytes:
        """Chunked request body (curl --data with unknown length, gRPC-web
        style clients). Trailers are read and dropped."""
        body = b""
        while True:
            szline = await reader.readline()
            if not szline:
                raise asyncio.IncompleteReadError(b"", None)
            size = int(szline.strip().split(b";")[0], 16)
            if size == 0:
                while True:
                    line = await reader.readline()
                    if line in (b"\r\n", b"\n", b""):
                        break
                return body
            if len(body) + size > MAX_BODY:
                raise _BodyTooLarge
            body += await reader.readexactly(size)
            await reader.readexactly(2)  # trailing CRLF

    async def _respond(self, writer, status: str, payload: dict,
                       keep: bool, head_only: bool = False,
                       extra_headers: str = ""):
        data = json.dumps(payload).encode()
        writer.write(
            f"HTTP/1.1 {status}\r\nContent-Type: application/json\r\n"
            f"Content-Length: {len(data)}\r\n{extra_headers}"
            f"Connection: {'keep-alive' if keep else 'close'}"
            f"\r\n\r\n".encode() + (b"" if head_only else data))
        await writer.drain()

    async def _handle_one(self, reader: asyncio.StreamReader,
                          writer: asyncio.StreamWriter) -> bool:
        """One request/response exchange; returns False to end the
        connection (keep-alive loop otherwise)."""
        request_line = await reader.readline()
        if not request_line or request_line in (b"\r\n", b"\n"):
            return False
        parts = request_line.decode("latin1").rstrip("\r\n").split(" ")
        if len(parts) != 3:
            await self._respond(writer, "400 Bad Request",
                               {"error": "malformed request line"}, False)
            return False
        method, path, version = parts
        headers: Dict[str, str] = {}
        hdr_bytes = 0
        while True:
            line = await reader.readline()
            if line in (b"\r\n", b"\n", b""):
                break
            hdr_bytes += len(line)
            if len(headers) > 256 or hdr_bytes > 64 << 10:
                # headers are attacker-controlled input too: bound them
                await self._respond(
                    writer, "431 Request Header Fields Too Large",
                    {"error": "too many/large headers"}, False)
                return False
            k, _, v = line.decode("latin1").partition(":")
            headers[k.strip().lower()] = v.strip()
        conn_hdr = headers.get("connection", "").lower()
        if version == "HTTP/1.0":
            keep = conn_hdr == "keep-alive"
        else:
            keep = conn_hdr != "close"
        # body — Content-Length or chunked, both bounded by MAX_BODY
        try:
            if "chunked" in headers.get("transfer-encoding", "").lower():
                body = await self._read_chunked(reader)
            else:
                n = int(headers.get("content-length", 0) or 0)
                if n > MAX_BODY:
                    raise _BodyTooLarge
                body = await reader.readexactly(n) if n else b""
        except _BodyTooLarge:
            # the unread body makes the stream unparseable: must close
            await self._respond(writer, "413 Payload Too Large",
                               {"error": f"body exceeds {MAX_BODY} bytes"},
                               False)
            return False
        except ValueError:
            await self._respond(writer, "400 Bad Request",
                               {"error": "bad framing headers"}, False)
            return False
        if method not in ALLOWED_METHODS:
            await self._respond(
                writer, "405 Method Not Allowed",
                {"error": f"method {method} not allowed"}, keep,
                extra_headers="Allow: " + ", ".join(
                    sorted(ALLOWED_METHODS)) + "\r\n")
            return keep
        if "?stream=1" in path or path.endswith("&stream=1"):
            await self._route_streaming(method, path, body, writer)
            return False  # streaming responses close the connection
        status, payload = await self._route(method, path, body)
        await self._respond(writer, status, payload, keep,
                            head_only=(method == "HEAD"))
        return keep

    async def _route(self, method: str, path: str, body: bytes):
        name = path.strip("/").split("?")[0].split("/")[0]
        if name == "-" or name == "":
            return "200 OK", {"status": "ok",
                              "routes": sorted(self._handles)}
        handle = self._handles.get(name)
        if handle is None:
            return "404 Not Found", {"error": f"no deployment {name!r}"}
        arg: Any = None
        if body:
            try:
                arg = json.loads(body)
            except json.JSONDecodeError:
                arg = body.decode()
        loop = asyncio.get_event_loop()
        # manual span, not span(): the await hands this coroutine's frame
        # back to the loop, so a thread-local span context must not stay
        # open across it (graftlint tracing-context-capture)
        from ray_tpu.util import tracing

        ms = tracing.manual_span("serve.proxy::request", {"route": name})
        try:
            # tracing.context: the handle's request span must parent
            # under the proxy span (one reconciled trace per HTTP
            # request), and handle.remote reads the thread-local ctx
            with tracing.context(ms.traceparent if ms else None):
                resp = (handle.remote(arg) if arg is not None
                        else handle.remote())
            result = await loop.run_in_executor(None, resp.result)
            return "200 OK", {"result": result}
        except Exception as e:  # noqa: BLE001
            if ms is not None:
                ms.finish(error=repr(e))
                ms = None
            return "500 Internal Server Error", {"error": str(e)}
        finally:
            if ms is not None:
                ms.finish()

    async def _route_streaming(self, method: str, path: str, body: bytes,
                               writer: asyncio.StreamWriter):
        """Chunked transfer: one JSON line per yielded item (reference
        HTTPProxy streaming responses, proxy.py:748 role)."""
        name = path.strip("/").split("?")[0].split("/")[0]
        handle = self._handles.get(name)
        if handle is None:
            data = json.dumps({"error": f"no deployment {name!r}"}).encode()
            writer.write(
                f"HTTP/1.1 404 Not Found\r\nContent-Length: {len(data)}"
                f"\r\nConnection: close\r\n\r\n".encode() + data)
            await writer.drain()
            return
        arg: Any = None
        if body:
            try:
                arg = json.loads(body)
            except json.JSONDecodeError:
                arg = body.decode()
        writer.write(
            b"HTTP/1.1 200 OK\r\nContent-Type: application/x-ndjson\r\n"
            b"Transfer-Encoding: chunked\r\nConnection: close\r\n\r\n")
        loop = asyncio.get_event_loop()
        from ray_tpu.util import tracing

        ms = tracing.manual_span("serve.proxy::stream", {"route": name})
        items = 0
        try:
            with tracing.context(ms.traceparent if ms else None):
                gen = (handle.options(stream=True).remote(arg)
                       if arg is not None
                       else handle.options(stream=True).remote())
            it = iter(gen)

            def _next():
                try:
                    return True, next(it)
                except StopIteration:
                    return False, None

            while True:
                more, item = await loop.run_in_executor(None, _next)
                if not more:
                    break
                items += 1
                chunk = (json.dumps({"result": item}) + "\n").encode()
                writer.write(f"{len(chunk):x}\r\n".encode() + chunk
                             + b"\r\n")
                await writer.drain()
            writer.write(b"0\r\n\r\n")
            await writer.drain()
        finally:
            if ms is not None:
                ms.finish({"items": items})

    def _run(self):
        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)

        async def main():
            self._stop_event = asyncio.Event()
            server = await asyncio.start_server(
                self._handle_conn, self.host, self.port)
            if self.port == 0:
                self.port = server.sockets[0].getsockname()[1]
            self._started.set()
            # Wait for stop() rather than serve_forever(): stopping the
            # loop mid-run_until_complete abandons this coroutine (the
            # "coroutine ignored GeneratorExit" teardown warning) and
            # leaks in-flight connection tasks.
            async with server:
                await self._stop_event.wait()

        try:
            self._loop.run_until_complete(main())
            # drain connection handlers still in flight at shutdown
            pending = [t for t in asyncio.all_tasks(self._loop)
                       if not t.done()]
            for t in pending:
                t.cancel()
            if pending:
                self._loop.run_until_complete(
                    asyncio.gather(*pending, return_exceptions=True))
        except (asyncio.CancelledError, RuntimeError):
            pass
        finally:
            self._loop.close()

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="serve_http_proxy")
        self._thread.start()
        self._started.wait(timeout=10)

    def stop(self) -> None:
        loop, ev = self._loop, self._stop_event
        if loop is not None and ev is not None:
            try:
                loop.call_soon_threadsafe(ev.set)
            except RuntimeError:
                pass  # loop already closed
        if self._thread is not None:
            self._thread.join(timeout=5)
