"""Multi-model serving plane: multiplexing, speculation, affinity.

Reference role: ``python/ray/serve/multiplex.py`` (``_ModelMultiplexWrapper``
— per-replica LRU of resident models behind ``serve.multiplexed``) grown
into a first-class subsystem over this repo's paged LLM engine:

- :class:`ModelRegistry` — per-replica catalog of many models (full
  weight sets and LoRA-style deltas over a shared base,
  ``models/delta.py``). Cold weights live in the ARENA OBJECT STORE via
  the public ``ray_tpu.put`` (spill-compressed tiers come free); a model
  materializes on first use and is LRU-evicted under a byte budget —
  never while an in-flight request pins it. All-pinned + over budget
  sheds with ``RequestShedError(reason="model_budget")``.
- :class:`MultiplexedLLMDeployment` — one replica serving N models:
  lazy per-model :class:`~ray_tpu.serve.llm.LLMDeployment` engines whose
  params page in/out through the registry (``params_provider`` /
  ``drop_params`` seam in ``serve/llm.py``). Load reports grow a
  resident-model digest + merged prefix digest, which
  ``serve/handle.py`` folds into routing (model affinity beats a
  swap-in; prefix affinity beats a prefill).
- :class:`SpeculativeLLMEngine` — greedy speculative decoding: a
  drafter proposes up to ``spec_k`` tokens per round and the target
  verifies them in ONE batched :func:`~ray_tpu.models.verify_step_paged`
  call (all-position logits). Emitted tokens are ALWAYS the target's
  exact greedy sequence: position ``j``'s draft is accepted iff it
  EQUALS the target argmax at ``j-1``'s continuation, and the first
  mismatch is replaced by that argmax (the "free correction"), so a
  round advances ``accepted+1`` tokens for one target step. Drafters:
  ``"ngram"`` (prompt-lookup — zero model cost) and ``"model"`` (a
  small draft model riding its OWN paged cache). A per-request
  acceptance EWMA falls the request back to plain decode when drafts
  stop landing (speculation must never lose more than the draft cost).

Everything here stays on the PUBLIC task/actor/object API (architecture
seam, CLAUDE.md): weights travel as ordinary objects, residency is read
via ``ray_tpu.util.state.object_store_tier``, and no experimental
transport is touched.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

import numpy as np

from ray_tpu.serve.admission import RequestShedError
from ray_tpu.serve.llm import LLMDeployment, LLMEngine, _Request


def _registry_metrics():
    try:
        from ray_tpu.util import metric_defs as md

        return {
            "swaps": md.get("rtpu_serve_model_swaps_total"),
            "resident": md.get("rtpu_serve_model_resident"),
            "bytes": md.get("rtpu_serve_model_resident_bytes"),
            "sheds": md.get("rtpu_serve_admission_sheds_total"),
        }
    except Exception:  # metrics plane unavailable (bare unit tests)
        return None


class ModelRegistry:
    """Per-replica model catalog with arena-paged weights.

    ``register`` parks a model's HOST weights in the object store (one
    ``ray_tpu.put`` — the store's spill tiers age cold models to disk
    for free; outside a runtime an in-process host copy stands in).
    ``ensure_resident`` materializes device params on demand, LRU-
    evicting unpinned models past ``budget_bytes``; ``pin``/``unpin``
    bracket every in-flight request so its model can NEVER be paged out
    mid-decode. A delta variant (``base=..., delta=...``) materializes
    via :func:`~ray_tpu.models.apply_delta` — untouched leaves are
    SHARED with the base, and the variant is charged only its unique
    bytes.

    Thread-safe; materialization runs under the lock (swap-in must be
    atomic against the evictor — the chaos test kills a replica exactly
    here and asserts no stranded store refs).
    """

    def __init__(self, *, budget_bytes: Optional[int] = None):
        from ray_tpu import config as _knobs

        self.budget_bytes = int(
            budget_bytes if budget_bytes is not None
            else _knobs.get("serve_model_budget_bytes"))
        self._lock = threading.RLock()
        self._entries: Dict[str, Dict[str, Any]] = {}
        self._metrics = _registry_metrics()

    # -- catalog -----------------------------------------------------------

    def register(self, model_id: str, config: Any = None, *,
                 params: Any = None, base: Optional[str] = None,
                 delta: Any = None, seed: int = 0) -> None:
        """Add a model. ``config`` is a preset name or
        ``TransformerConfig`` (inherited from ``base`` when omitted);
        ``params`` is an optional host pytree (random-initialized when
        absent and no delta). ``base``+``delta`` registers a LoRA-style
        variant over an already-registered base."""
        import jax

        from ray_tpu import models

        with self._lock:
            if model_id in self._entries:
                raise ValueError(f"model {model_id!r} already registered")
            if base is not None:
                be = self._entries.get(base)
                if be is None:
                    raise ValueError(
                        f"base {base!r} of {model_id!r} is not registered")
                if delta is None:
                    raise ValueError(
                        f"variant {model_id!r} names base={base!r} but "
                        "carries no delta")
                cfg = be["config"] if config is None else config
            else:
                if config is None:
                    raise ValueError(
                        f"model {model_id!r} needs a config (or a base)")
                cfg = config
            if isinstance(cfg, str):
                cfg = models.get_config(cfg)

            host = None
            nbytes = 0
            if base is None:
                if params is None:
                    params = models.init_params(
                        jax.random.PRNGKey(seed), cfg)
                host = jax.tree_util.tree_map(np.asarray, params)
                nbytes = models.params_bytes(host)
            else:
                # the variant's host payload is the (small) delta; its
                # RESIDENT charge is the rebuilt projection leaves plus
                # the factors — every other leaf is shared with the base
                host = jax.tree_util.tree_map(np.asarray, delta)
                L, d = cfg.n_layers, cfg.d_model
                itemsize = np.dtype(cfg.param_dtype).itemsize
                shapes = {"wq": d * cfg.n_heads * cfg.hdim,
                          "wk": d * cfg.kv_heads * cfg.hdim,
                          "wv": d * cfg.kv_heads * cfg.hdim,
                          "wo": cfg.n_heads * cfg.hdim * d}
                nbytes = models.delta_bytes(host) + sum(
                    L * shapes[t] * itemsize for t in host["targets"])

            ref = None
            try:
                import ray_tpu

                if ray_tpu.is_initialized():
                    ref = ray_tpu.put(host)
                    host = None  # the store owns the cold copy
            except Exception:
                ref = None
            self._entries[model_id] = {
                "config": cfg, "ref": ref, "host": host, "bytes": nbytes,
                "params": None, "pins": 0, "last_used": 0.0,
                "swaps_in": 0, "swaps_out": 0, "base": base,
                "evict_cb": None,
            }

    def __contains__(self, model_id: str) -> bool:
        with self._lock:
            return model_id in self._entries

    def models(self) -> List[str]:
        with self._lock:
            return list(self._entries)

    def config_of(self, model_id: str):
        with self._lock:
            return self._entries[model_id]["config"]

    def bind(self, model_id: str, evict_cb: Callable[[], None]) -> None:
        """Attach the engine-side drop hook eviction must fire (the
        engine and the registry reference the SAME params pytree)."""
        with self._lock:
            self._entries[model_id]["evict_cb"] = evict_cb

    # -- pinning (in-flight requests) --------------------------------------

    def pin(self, model_id: str) -> None:
        with self._lock:
            self._entries[model_id]["pins"] += 1

    def unpin(self, model_id: str) -> None:
        with self._lock:
            e = self._entries[model_id]
            if e["pins"] <= 0:
                raise RuntimeError(f"unpin of unpinned model {model_id!r}")
            e["pins"] -= 1

    # -- residency ---------------------------------------------------------

    def _fetch_host(self, e: Dict[str, Any]):
        if e["host"] is not None:
            return e["host"]
        import ray_tpu

        return ray_tpu.get(e["ref"])

    def _materialize(self, e: Dict[str, Any]):
        import jax.numpy as jnp
        from jax import tree_util

        from ray_tpu import models

        host = self._fetch_host(e)
        if e["base"] is None:
            return tree_util.tree_map(jnp.asarray, host)
        base_params = self._ensure_resident_locked(e["base"])
        delta = tree_util.tree_map(jnp.asarray, host)
        return models.apply_delta(base_params, delta)

    def resident_bytes(self) -> int:
        with self._lock:
            return sum(e["bytes"] for e in self._entries.values()
                       if e["params"] is not None)

    def _evict_for(self, need: int, keep: str) -> None:
        """Make room for ``need`` bytes (caller holds the lock)."""
        if self.budget_bytes <= 0:
            return
        while True:
            resident = sum(e["bytes"] for e in self._entries.values()
                           if e["params"] is not None)
            if resident + need <= self.budget_bytes:
                return
            victims = [(mid, e) for mid, e in self._entries.items()
                       if e["params"] is not None and e["pins"] == 0
                       and mid != keep]
            if not victims:
                if self._metrics:
                    self._metrics["sheds"].inc(
                        tags={"reason": "model_budget"})
                raise RequestShedError(
                    f"model {keep!r} needs {need} resident bytes but the "
                    f"budget ({self.budget_bytes}) is held by pinned "
                    "models", reason="model_budget")
            mid, e = min(victims, key=lambda kv: kv[1]["last_used"])
            e["params"] = None
            e["swaps_out"] += 1
            if self._metrics:
                self._metrics["swaps"].inc(tags={"direction": "out"})
            cb = e["evict_cb"]
            if cb is not None:
                try:
                    cb()
                except Exception:
                    pass

    def _ensure_resident_locked(self, model_id: str):
        e = self._entries.get(model_id)
        if e is None:
            raise KeyError(f"unknown model {model_id!r}")
        if e["params"] is None:
            self._evict_for(e["bytes"], keep=model_id)
            e["params"] = self._materialize(e)
            e["swaps_in"] += 1
            if self._metrics:
                self._metrics["swaps"].inc(tags={"direction": "in"})
        e["last_used"] = time.monotonic()
        return e["params"]

    def ensure_resident(self, model_id: str):
        """Materialized device params for ``model_id`` (swap-in on
        miss, LRU eviction for room). Raises ``RequestShedError``
        (reason ``model_budget``) when nothing can be evicted."""
        with self._lock:
            return self._ensure_resident_locked(model_id)

    # -- introspection -----------------------------------------------------

    def _tier(self, e: Dict[str, Any]) -> str:
        if e["params"] is not None:
            return "hbm"
        if e["ref"] is None:
            return "host"
        try:
            from ray_tpu.util.state import object_store_tier

            t = object_store_tier(e["ref"])
            return {"shm": "host", "spilled": "spilled"}.get(t, "host")
        except Exception:
            return "host"

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            out = {
                mid: {"state": self._tier(e), "bytes": e["bytes"],
                      "pins": e["pins"], "swaps_in": e["swaps_in"],
                      "swaps_out": e["swaps_out"], "base": e["base"],
                      "resident": e["params"] is not None}
                for mid, e in self._entries.items()
            }
        if self._metrics:
            by_state: Dict[str, int] = {}
            for rec in out.values():
                by_state[rec["state"]] = by_state.get(rec["state"], 0) + 1
            for state, n in by_state.items():
                self._metrics["resident"].set(n, tags={"state": state})
            self._metrics["bytes"].set(
                sum(r["bytes"] for r in out.values() if r["resident"]))
        return out

    def free(self) -> None:
        """Drop every store ref (replica shutdown — the chaos test
        asserts no stranded arena weight refs survive a close)."""
        with self._lock:
            refs = [e.pop("ref") for e in self._entries.values()
                    if e.get("ref") is not None]
            for e in self._entries.values():
                e["ref"] = None
                e["params"] = None
        if refs:
            try:
                import ray_tpu

                ray_tpu.free(refs)
            except Exception:
                pass


# -- drafters ---------------------------------------------------------------


class _NgramDraft:
    """Prompt-lookup drafting (assisted-generation style): the last
    ``n``-gram of the request's history is searched backwards through
    the history itself and the tokens FOLLOWING the most recent earlier
    occurrence become the draft. Zero model cost — acceptance is pure
    upside — and strong exactly where speculation pays most (templated
    continuations, code, the repetitive tails greedy decoding produces).
    """

    def __init__(self, n: int = 3):
        self.n = max(1, int(n))

    def propose(self, req: _Request, k: int, engine: "SpeculativeLLMEngine",
                slot: int) -> List[int]:
        hist = engine._spec_state(req)["hist"]
        n = min(self.n, len(hist) - 1)
        while n >= 1:
            pat = hist[-n:]
            for s in range(len(hist) - n - 1, -1, -1):
                if hist[s:s + n] == pat:
                    return [int(t) for t in hist[s + n:s + n + k]]
            n -= 1
        return []

    def prune(self, live: Set[_Request]) -> None:  # stateless
        pass


class _ModelDraft:
    """Model drafting: a small draft model (same vocab as the target)
    rides its OWN paged cache with one statically-owned table per
    target slot. Per round it catches up on committed history in
    chunks (re-feeding overwrites any stale rejected-draft KV — the
    same write-before-gather guarantee the verify path relies on), then
    rolls the draft forward token by token. ``fed`` counts COMMITTED
    tokens only, so a rejected draft costs nothing to undo."""

    def __init__(self, config: Any = None, params: Any = None, *,
                 seed: int = 1):
        self._config = config
        self._params_in = params
        self._seed = seed
        self._ready = False

    def _ensure(self, engine: "SpeculativeLLMEngine") -> None:
        if self._ready:
            return
        import jax

        from ray_tpu import models

        cfg = self._config if self._config is not None else engine.config
        if isinstance(cfg, str):
            cfg = models.get_config(cfg)
        if cfg.vocab_size != engine.config.vocab_size:
            raise ValueError(
                f"draft vocab {cfg.vocab_size} != target vocab "
                f"{engine.config.vocab_size} (tokens must be "
                "interchangeable)")
        self.cfg = cfg
        self.params = (self._params_in if self._params_in is not None
                       else models.init_params(
                           jax.random.PRNGKey(self._seed), cfg))
        self.S = engine.max_slots
        self.W = engine._tbl_width
        self.C = engine.prefill_chunk
        nb = self.S * self.W
        self._cache = models.init_cache_paged(cfg, nb,
                                              engine.pool.block_size)
        self._tables = np.arange(nb, dtype=np.int32).reshape(self.S,
                                                             self.W)

        def raw(params, cache, tokens, tables, pos, nvalid, active):
            from ray_tpu.models import decode_step_paged

            return decode_step_paged(params, cache, tokens, tables, pos,
                                     nvalid, cfg, active=active)

        from ray_tpu.util.device_plane import registered_jit

        self._step = registered_jit(raw, name="serve::mux_decode_step",
                                    component="serve",
                                    donate_argnums=(1,))
        self._bound: List[Optional[_Request]] = [None] * self.S
        self._fed = [0] * self.S
        self._ready = True

    def _advance(self, slot: int, toks: List[int], pos0: int) -> np.ndarray:
        import jax
        import jax.numpy as jnp

        tokens = np.zeros((self.S, self.C), np.int32)
        nvalid = np.zeros(self.S, np.int32)
        active = np.zeros(self.S, bool)
        pos = np.zeros(self.S, np.int32)
        tokens[slot, :len(toks)] = toks
        nvalid[slot] = len(toks)
        active[slot] = True
        pos[slot] = pos0
        logits, self._cache = self._step(
            self.params, self._cache, jnp.asarray(tokens),
            jnp.asarray(self._tables), jnp.asarray(pos),
            jnp.asarray(nvalid), jnp.asarray(active))
        return np.asarray(jax.device_get(logits))[slot]

    def propose(self, req: _Request, k: int, engine: "SpeculativeLLMEngine",
                slot: int) -> List[int]:
        self._ensure(engine)
        if self._bound[slot] is not req:
            self._bound[slot] = req
            self._fed[slot] = 0
        hist = engine._spec_state(req)["hist"]
        fed = self._fed[slot]
        logits = None
        while fed < len(hist):
            n = min(self.C, len(hist) - fed)
            logits = self._advance(slot, hist[fed:fed + n], fed)
            fed += n
        self._fed[slot] = fed
        if logits is None:  # pragma: no cover - hist grows every round
            return []
        out = [int(np.argmax(logits))]
        while len(out) < k:
            logits = self._advance(slot, [out[-1]], fed + len(out) - 1)
            out.append(int(np.argmax(logits)))
        return out[:k]

    def prune(self, live: Set[_Request]) -> None:
        if not self._ready:
            return
        for i, r in enumerate(self._bound):
            if r is not None and r not in live:
                self._bound[i] = None
                self._fed[i] = 0


# -- speculative engine ------------------------------------------------------


class SpeculativeLLMEngine(LLMEngine):
    """Greedy speculative decoding over the paged slot engine.

    Every step is ONE batched :func:`~ray_tpu.models.verify_step_paged`
    call (all-position logits): prefilling slots feed prompt chunks
    exactly as the base engine does, while decoding slots feed
    ``[last_token, d_1..d_k']`` and accept the longest draft prefix that
    matches the target's own argmax chain — emitted tokens are exactly
    the plain-greedy sequence by construction (the acceptance check IS
    equality with the target argmax, and the first mismatch emits that
    argmax instead). KV written at rejected positions is never attended
    (the visibility mask stops at the request's committed position) and
    is overwritten by the next round's feed before it could be.

    Requires ``paged=True`` and greedy sampling (``temperature<=0``) —
    lossless speculation is only defined against a deterministic target.
    """

    SPEC_WARMUP = 6  # rounds before the acceptance EWMA may trip

    def __init__(self, config, params=None, *, spec_k: Optional[int] = None,
                 drafter: str = "ngram", draft_model: Any = None,
                 draft_params: Any = None, draft_seed: int = 1,
                 spec_accept_floor: Optional[float] = None,
                 ngram: int = 3, **kw):
        from ray_tpu import config as _knobs

        self.spec_k = int(spec_k if spec_k is not None
                          else _knobs.get("spec_k"))
        if self.spec_k < 1:
            raise ValueError(f"spec_k must be >= 1, got {self.spec_k}")
        self.spec_accept_floor = float(
            spec_accept_floor if spec_accept_floor is not None
            else _knobs.get("spec_accept_floor"))
        if kw.get("temperature", 0.0) > 0.0:
            raise ValueError(
                "speculative decoding requires greedy sampling "
                "(temperature <= 0): lossless acceptance is defined "
                "against the target's deterministic argmax chain")
        if not kw.get("paged", True):
            raise ValueError("speculative decoding requires paged=True")
        # the slot grid's chunk width carries BOTH prefill chunks and
        # the verify window [last, d1..dk]
        pc = int(kw.get("prefill_chunk")
                 or _knobs.get("llm_prefill_chunk"))
        kw["prefill_chunk"] = max(pc, self.spec_k + 1)
        super().__init__(config, params, **kw)

        import jax

        from ray_tpu.util.device_plane import registered_jit

        self._verify_fn = registered_jit(self._raw_verify_paged,
                                         name="serve::verify_step_paged",
                                         component="serve",
                                         donate_argnums=(1,))
        if drafter == "ngram":
            self._draft = _NgramDraft(n=ngram)
        elif drafter == "model":
            self._draft = _ModelDraft(draft_model, draft_params,
                                      seed=draft_seed)
        else:
            raise ValueError(
                f"unknown drafter {drafter!r} (want 'ngram' or 'model')")
        self.drafter = drafter
        # per-request speculation state, identity-keyed (_Request is
        # eq=False); pruned to live slots every step
        self._spec: Dict[_Request, Dict[str, Any]] = {}
        self.stats.update(spec_rounds=0, spec_proposed=0,
                          spec_accepted=0, spec_fallbacks=0)

    @staticmethod
    def _init_metrics():
        m = LLMEngine._init_metrics()
        if m is None:
            return None
        try:
            from ray_tpu.util import metric_defs as md

            m.update(
                spec_rounds=md.get("rtpu_spec_rounds_total"),
                spec_proposed=md.get("rtpu_spec_proposed_tokens_total"),
                spec_accepted=md.get("rtpu_spec_accepted_tokens_total"),
                spec_fallbacks=md.get("rtpu_spec_fallbacks_total"))
        except Exception:
            pass
        return m

    def _raw_verify_paged(self, params, cache, tokens, tables, pos,
                          nvalid, active):
        from ray_tpu.models import verify_step_paged

        return verify_step_paged(params, cache, tokens, tables, pos,
                                 nvalid, self.config, active=active)

    def _spec_state(self, req: _Request) -> Dict[str, Any]:
        st = self._spec.get(req)
        if st is None:
            st = {"ewma": 1.0, "rounds": 0, "off": False, "hist": None}
            self._spec[req] = st
        return st

    def step(self) -> bool:
        """The base loop with multi-token emission: a decoding slot may
        route up to ``accepted+1`` tokens per step."""
        import jax
        import jax.numpy as jnp

        active_now, have_pending = self._sweep_and_admit()
        if active_now == 0:
            if self._spec:
                self._spec.clear()
                self._draft.prune(set())
            self._sample_gauges()
            return have_pending
        self._ensure_params()

        t0 = time.perf_counter()
        emitted, nvalid = self._advance_spec(jax, jnp)
        if self.stats["steps"] > 0:
            self.admission.observe_step(time.perf_counter() - t0)

        now = time.monotonic()
        for i, req in enumerate(self._slots):
            if req is None:
                continue
            if req.consumed < len(req.prompt):
                req.consumed += int(nvalid[i])
                if req.consumed < len(req.prompt):
                    continue  # still prefilling; nothing sampled yet
            for tok in emitted[i]:
                req.last_token = tok
                req.generated += 1
                self._observe_emit(req, now)
                if req.prefill_only:
                    self._emit_prefill_export(i, req, tok, jax, jnp)
                    break  # slot cleared by the export
                req.emit(tok)
                self.stats["tokens_generated"] += 1
                if req.generated >= req.max_new_tokens or (
                        req.eos is not None and tok == req.eos):
                    with self._lock:
                        self._release_blocks(req, insert=True)
                    req.emit(None)
                    self._slots[i] = None
                    break
        live = {r for r in self._slots if r is not None}
        if len(self._spec) > len(live):
            self._spec = {r: st for r, st in self._spec.items()
                          if r in live}
            self._draft.prune(live)
        self.stats["steps"] += 1
        self._sample_gauges()
        return True

    def _advance_spec(self, jax, jnp) -> Tuple[List[List[int]], np.ndarray]:
        """One verify round: build the batch (prefill chunks as usual,
        draft windows for decoders), run the all-logits step, accept.
        Returns per-slot emitted-token lists plus the fed counts (the
        step loop advances ``consumed`` off them for prefill rows)."""
        C = self.prefill_chunk
        tokens = np.zeros((self.max_slots, C), np.int32)
        nvalid = np.zeros(self.max_slots, np.int32)
        active = np.zeros(self.max_slots, bool)
        pos = np.zeros(self.max_slots, np.int32)
        tables = np.zeros((self.max_slots, self._tbl_width), np.int32)
        drafted: List[List[int]] = [[] for _ in range(self.max_slots)]
        for i, req in enumerate(self._slots):
            if req is None:
                continue
            active[i] = True
            pos[i] = req.pos
            tables[i, :len(req.table)] = req.table
            if req.consumed < len(req.prompt):
                n = min(C, len(req.prompt) - req.consumed)
                tokens[i, :n] = req.prompt[req.consumed:req.consumed + n]
                nvalid[i] = n
                continue
            st = self._spec_state(req)
            if st["hist"] is None:
                # first decode round: committed history = prompt + the
                # boundary token sampled when prefill finished
                st["hist"] = req.prompt.tolist() + [req.last_token]
            d: List[int] = []
            if not st["off"] and not req.prefill_only:
                # clamp so the round can never write past the claimed
                # table: accepted+1 <= k'+1 stays within max_new
                k = min(self.spec_k, C - 1,
                        req.max_new_tokens - req.generated - 1)
                if k > 0:
                    d = self._draft.propose(req, k, self, i)[:k]
            drafted[i] = d
            tokens[i, 0] = req.last_token
            for j, t in enumerate(d):
                tokens[i, 1 + j] = t
            nvalid[i] = 1 + len(d)

        logits, self._cache = self._verify_fn(
            self.params, self._cache, jnp.asarray(tokens),
            jnp.asarray(tables), jnp.asarray(pos), jnp.asarray(nvalid),
            jnp.asarray(active))
        logits_h = np.asarray(jax.device_get(logits))  # [B, C, V]

        emitted: List[List[int]] = [[] for _ in range(self.max_slots)]
        for i, req in enumerate(self._slots):
            if req is None:
                continue
            n = int(nvalid[i])
            if req.consumed < len(req.prompt):
                req.pos += n
                if req.consumed + n >= len(req.prompt):
                    # prompt completes this step: the last valid
                    # position's logits seed generation (same token the
                    # base engine samples)
                    emitted[i] = [int(np.argmax(logits_h[i, n - 1]))]
                continue
            d = drafted[i]
            toks = [int(np.argmax(logits_h[i, 0]))]
            accepted = 0
            for j, dt in enumerate(d):
                if dt != toks[-1]:
                    break  # mismatch: toks[-1] IS the correction
                accepted += 1
                toks.append(int(np.argmax(logits_h[i, j + 1])))
            # commit exactly the accepted prefix + the target token:
            # pos advances past what the greedy chain confirmed, never
            # past what was fed
            req.pos += accepted + 1
            st = self._spec_state(req)
            if d:
                m = self._metrics if (self._metrics
                                      and "spec_rounds" in self._metrics
                                      ) else None
                self.stats["spec_rounds"] += 1
                self.stats["spec_proposed"] += len(d)
                self.stats["spec_accepted"] += accepted
                if m:
                    m["spec_rounds"].inc()
                    m["spec_proposed"].inc(len(d))
                    if accepted:
                        m["spec_accepted"].inc(accepted)
                st["rounds"] += 1
                st["ewma"] = 0.5 * st["ewma"] + 0.5 * (accepted / len(d))
                if (st["rounds"] >= self.SPEC_WARMUP
                        and st["ewma"] < self.spec_accept_floor):
                    # acceptance collapsed: this request decodes plain
                    # from here on (k'=0 rides the same verify fn)
                    st["off"] = True
                    self.stats["spec_fallbacks"] += 1
                    if m:
                        m["spec_fallbacks"].inc()
            if st["hist"] is not None:
                st["hist"].extend(toks)
            emitted[i] = toks
        return emitted, nvalid

    def kv_state(self) -> Dict[str, Any]:
        out = super().kv_state()
        out["spec"] = {k: self.stats[k] for k in
                       ("spec_rounds", "spec_proposed", "spec_accepted",
                        "spec_fallbacks")}
        return out


class SpeculativeLLMDeployment(LLMDeployment):
    """:class:`~ray_tpu.serve.llm.LLMDeployment` whose engine decodes
    speculatively. Extra kwargs: ``spec_k``, ``drafter`` ("ngram" |
    "model"), ``draft_model``/``draft_params`` (the "model" drafter's
    config + optional host weights), ``spec_accept_floor``."""

    def __init__(self, model="llama-debug", *, spec_k: Optional[int] = None,
                 drafter: str = "ngram", draft_model: Any = None,
                 draft_params: Any = None, draft_seed: int = 1,
                 spec_accept_floor: Optional[float] = None,
                 ngram: int = 3, **kw):
        self._spec_opts = dict(spec_k=spec_k, drafter=drafter,
                               draft_model=draft_model,
                               draft_params=draft_params,
                               draft_seed=draft_seed,
                               spec_accept_floor=spec_accept_floor,
                               ngram=ngram)
        super().__init__(model, **kw)

    def _engine_factory(self, *args, **kw) -> SpeculativeLLMEngine:
        return SpeculativeLLMEngine(*args, **kw, **self._spec_opts)


# -- the multiplexed deployment ---------------------------------------------


class MultiplexedLLMDeployment:
    """One replica serving MANY models: per-model engines created
    lazily, weights paged through a shared :class:`ModelRegistry`.

    ``models_spec`` maps ``model_id`` to a preset name, a
    ``TransformerConfig``, or a dict ``{"config": ..., "params": ...,
    "base": ..., "delta": ..., "seed": ...}`` (base+delta registers a
    LoRA-style variant). Requests address a model with
    ``model_id=`` (default: the first registered model)::

        dep = MultiplexedLLMDeployment(
            {"m0": "llama-debug", "m1": "gpt2-debug"},
            budget_bytes=1 << 20)
        for tok in dep([1, 2, 3], 16, model_id="m1"):
            ...

    Each model gets its own :class:`~ray_tpu.serve.llm.LLMDeployment`
    (loop thread, admission, streaming, paged KV + prefix trie) the
    first time a request lands on it — the registry's swap counters are
    the lazy-paging proof the multiplexing A/B asserts on. A request
    PINS its model for its stream's lifetime, so eviction (LRU under
    ``budget_bytes``) only ever fires on idle engines; the engine's
    ``params_provider`` re-acquires on the next step after a page-out.
    ``load_state`` aggregates the per-model engines and adds the
    resident-model digest + merged prefix digest that
    ``serve/handle.py`` routes on.
    """

    def __init__(self, models_spec, *, default_model: Optional[str] = None,
                 budget_bytes: Optional[int] = None,
                 speculative: bool = False, spec_k: Optional[int] = None,
                 drafter: str = "ngram", draft_model: Any = None,
                 draft_params: Any = None,
                 spec_accept_floor: Optional[float] = None,
                 max_slots: int = 8, max_len: int = 256,
                 temperature: float = 0.0, seed: int = 0,
                 block_size: Optional[int] = None,
                 num_blocks: Optional[int] = None,
                 prefill_chunk: Optional[int] = None,
                 prefix_cache: bool = True, slo: Optional[Any] = None,
                 stream_batch: int = 1):
        if isinstance(models_spec, (list, tuple)):
            models_spec = {mid: mid for mid in models_spec}
        if not models_spec:
            raise ValueError("models_spec is empty")
        self.registry = ModelRegistry(budget_bytes=budget_bytes)
        for mid, spec in models_spec.items():
            if isinstance(spec, dict):
                self.registry.register(
                    mid, spec.get("config"), params=spec.get("params"),
                    base=spec.get("base"), delta=spec.get("delta"),
                    seed=spec.get("seed", seed))
            else:
                self.registry.register(mid, spec, seed=seed)
        self._default = default_model or next(iter(models_spec))
        if self._default not in self.registry:
            raise ValueError(
                f"default_model {self._default!r} is not registered")
        self._dep_kw = dict(max_slots=max_slots, max_len=max_len,
                            temperature=temperature, seed=seed,
                            paged=True, block_size=block_size,
                            num_blocks=num_blocks,
                            prefill_chunk=prefill_chunk,
                            prefix_cache=prefix_cache, slo=slo,
                            stream_batch=stream_batch)
        self._spec_kw = (dict(spec_k=spec_k, drafter=drafter,
                              draft_model=draft_model,
                              draft_params=draft_params,
                              spec_accept_floor=spec_accept_floor)
                         if speculative else None)
        self._deps: Dict[str, LLMDeployment] = {}
        self._dep_lock = threading.Lock()
        self._ident: Optional[Dict[str, Any]] = None

    # -- engine lifecycle --------------------------------------------------

    def _get_dep(self, model_id: str) -> LLMDeployment:
        with self._dep_lock:
            dep = self._deps.get(model_id)
            if dep is None:
                cfg = self.registry.config_of(model_id)
                params = self.registry.ensure_resident(model_id)
                if self._spec_kw is not None:
                    dep = SpeculativeLLMDeployment(cfg, params=params,
                                                   **self._spec_kw,
                                                   **self._dep_kw)
                else:
                    dep = LLMDeployment(cfg, params=params,
                                        **self._dep_kw)
                dep._model_id = model_id
                dep.engine.params_provider = (
                    lambda m=model_id: self.registry.ensure_resident(m))
                self.registry.bind(model_id, dep.engine.drop_params)
                self._deps[model_id] = dep
        return dep

    # -- request path ------------------------------------------------------

    def __call__(self, prompt_tokens, max_new_tokens: int = 16,
                 model_id: Optional[str] = None, eos: Optional[int] = None,
                 deadline_s: Optional[float] = None):
        mid = model_id or self._default
        if mid not in self.registry:
            raise ValueError(
                f"unknown model_id {mid!r}; registered: "
                f"{sorted(self.registry.models())}")
        # pin FIRST: between the residency check and the stream's end
        # this model must be un-evictable (the engine only reads params
        # while it has active work, and active work implies this pin)
        self.registry.pin(mid)
        try:
            self.registry.ensure_resident(mid)
            dep = self._get_dep(mid)
            inner = dep(prompt_tokens, max_new_tokens, eos=eos,
                        deadline_s=deadline_s)
        except BaseException:
            self.registry.unpin(mid)
            raise

        def stream():
            try:
                yield from inner
            finally:
                self.registry.unpin(mid)

        return stream()

    # -- replica surface (serve protocol) ----------------------------------

    def identity(self) -> Dict[str, Any]:
        if self._ident is None or self._ident.get("actor") is None:
            try:
                import ray_tpu

                ctx = ray_tpu.get_runtime_context()
                self._ident = {"actor": ctx.get_actor_id(),
                               "node": ctx.get_node_id()}
            except Exception:
                import os

                self._ident = {
                    "actor": None,
                    "node": os.environ.get("RTPU_NODE_ID",
                                           f"proc-{os.getpid()}")}
        return self._ident

    def stats(self) -> Dict[str, Any]:
        with self._dep_lock:
            deps = dict(self._deps)
        out: Dict[str, Any] = {"models": self.registry.snapshot()}
        for mid, dep in deps.items():
            out[mid] = dep.stats()
        return out

    def load_state(self) -> Dict[str, Any]:
        with self._dep_lock:
            deps = dict(self._deps)
        states = {mid: dep.load_state() for mid, dep in deps.items()}
        ident = self.identity()
        out: Dict[str, Any] = {
            "inflight": sum(s["inflight"] for s in states.values()),
            "kv_free": sum(s["kv_free"] for s in states.values()),
            "kv_total": sum(s["kv_total"] for s in states.values()),
            "role": "colocated",
            "node": ident["node"],
            "actor": ident["actor"],
            "queued": sum(s["queued"] for s in states.values()),
            "max_slots": (sum(s["max_slots"] for s in states.values())
                          or self._dep_kw["max_slots"]),
            "block_size": next((s["block_size"] for s in states.values()
                                if s.get("block_size")), 0),
        }
        snap = self.registry.snapshot()
        out["models"] = {
            mid: {"state": rec["state"],
                  "inflight": states.get(mid, {}).get("inflight", 0),
                  "swaps_in": rec["swaps_in"],
                  "swaps_out": rec["swaps_out"]}
            for mid, rec in snap.items()
        }
        agg: Dict[str, int] = {}
        for s in states.values():
            for key, w in s.get("prefix_digest", []):
                agg[key] = agg.get(key, 0) + int(w)
        try:
            from ray_tpu import config as _knobs

            top = int(_knobs.get("serve_prefix_digest_top"))
        except Exception:
            top = 8
        out["prefix_digest"] = sorted(
            agg.items(), key=lambda kv: -kv[1])[:top]
        return out

    def check_health(self) -> None:
        with self._dep_lock:
            deps = list(self._deps.values())
        for dep in deps:
            dep.check_health()

    def close(self) -> None:
        with self._dep_lock:
            deps, self._deps = list(self._deps.values()), {}
        for dep in deps:
            try:
                dep.close()
            except Exception:
                pass
        self.registry.free()
