"""Continuous-batching LLM serving: slot engine + serve deployment.

Reference role: ``python/ray/serve/batching.py`` (request batching) +
streaming responses, joined into an LLM decode loop — the reference has
no LLM engine; this is the TPU-first differentiator (CLAUDE.md round-5
note). Design follows Orca-style token-level continuous batching:

- The engine owns ONE jitted step (:func:`decode_step_multi`) over a
  fixed slot grid [max_slots]: static shapes, compiled once. Every
  iteration each active slot advances exactly one token — slots still
  consuming their PROMPT feed the next prompt token, slots generating
  feed back their last sample. New requests therefore join the in-flight
  batch immediately (admission = claiming a free slot), and finished
  requests free their slot between steps; nobody waits for a "batch" to
  drain. Prompt prefill thus shares the decode program (one compile); a
  chunked-prefill fast path is a possible future optimization, at the
  cost of a second compiled program per chunk shape.
- Slots need no cache clearing on reuse: the attention band masks
  ``kpos <= pos``, and pos restarts at 0, so stale K/V from the previous
  occupant is never visible.
- The engine is serve-independent (testable standalone); the
  :class:`LLMDeployment` wrapper runs it on a background thread inside a
  ``max_concurrency`` replica and streams tokens to each caller through
  the ordinary streaming-generator path.
"""

from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import numpy as np


@dataclass(eq=False)   # identity semantics: generated __eq__ would
class _Request:        # elementwise-compare the prompt arrays and raise
    prompt: np.ndarray                 # [P] int32
    max_new_tokens: int
    # token sink: int token, None = done, Exception = engine failure
    emit: Callable[[Any], None]
    consumed: int = 0                  # prompt tokens fed so far
    generated: int = 0
    last_token: int = 0
    eos: Optional[int] = None
    cancelled: bool = False


class LLMEngine:
    """Slot-based continuous-batching decode engine over one model.

    ``submit`` is thread-safe; ``step`` must be called from ONE driver
    thread (the deployment's loop thread) and returns whether any work
    remains. Greedy sampling by default; ``temperature`` > 0 samples.
    """

    def __init__(self, config, params=None, *, max_slots: int = 8,
                 max_len: int = 256, temperature: float = 0.0,
                 seed: int = 0):
        import jax
        import jax.numpy as jnp

        from ray_tpu import models

        if isinstance(config, str):
            config = models.get_config(config)
        self.config = config
        self.max_slots = max_slots
        self.max_len = max_len
        self.temperature = temperature
        if params is None:
            params = models.init_params(jax.random.PRNGKey(seed), config)
        self.params = params
        self._cache = models.init_cache_multi(config, max_slots, max_len)
        self._step_fn = jax.jit(self._raw_step)
        self._rng = np.random.default_rng(seed)
        self._lock = threading.Lock()
        self._pending: List[_Request] = []
        self._slots: List[Optional[_Request]] = [None] * max_slots
        self.stats = {"steps": 0, "tokens_generated": 0,
                      "max_concurrent": 0, "requests": 0}

    def _raw_step(self, params, cache, tokens, active):
        from ray_tpu.models import decode_step_multi

        return decode_step_multi(params, cache, tokens, self.config,
                                 active=active)

    # -- thread-safe intake ------------------------------------------------

    def submit(self, prompt, max_new_tokens: int,
               emit: Callable[[Any], None],
               eos: Optional[int] = None) -> "_Request":
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if len(prompt) == 0:
            raise ValueError("empty prompt")
        if len(prompt) + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens "
                f"({max_new_tokens}) exceeds the engine's max_len "
                f"({self.max_len})")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        req = _Request(prompt, max_new_tokens, emit, eos=eos)
        with self._lock:
            self._pending.append(req)
            self.stats["requests"] += 1
        return req

    def cancel(self, req: "_Request") -> None:
        """Abandon a request: pending entries are dropped immediately; an
        in-slot request frees its slot at the next step without emitting
        further tokens (client disconnect must not leave zombie slots)."""
        with self._lock:
            req.cancelled = True
            if req in self._pending:
                self._pending.remove(req)

    def abort_all(self, error: BaseException) -> None:
        """Fail every outstanding request (decode loop died)."""
        with self._lock:
            victims = [r for r in self._slots if r is not None]
            victims += self._pending
            self._pending.clear()
            self._slots = [None] * self.max_slots
        for r in victims:
            try:
                r.emit(error)
            except Exception:
                pass

    # -- driver-thread loop body ------------------------------------------

    def _reset_slot(self, i: int) -> None:
        import jax.numpy as jnp

        self._cache["pos"] = self._cache["pos"].at[i].set(jnp.int32(0))

    def step(self) -> bool:
        """Admit pending requests, advance every active slot one token,
        route new tokens to their requests. Returns True if any slot is
        active or requests are waiting."""
        import jax
        import jax.numpy as jnp

        with self._lock:
            for i in range(self.max_slots):
                if self._slots[i] is not None and self._slots[i].cancelled:
                    self._slots[i] = None
                if self._slots[i] is None and self._pending:
                    self._slots[i] = self._pending.pop(0)
                    self._reset_slot(i)
            active_now = sum(r is not None for r in self._slots)
            self.stats["max_concurrent"] = max(
                self.stats["max_concurrent"], active_now)
            have_pending = bool(self._pending)
        if active_now == 0:
            return have_pending

        tokens = np.zeros((self.max_slots, 1), np.int32)
        active = np.zeros(self.max_slots, bool)
        for i, req in enumerate(self._slots):
            if req is None:
                continue
            active[i] = True
            if req.consumed < len(req.prompt):
                tokens[i, 0] = req.prompt[req.consumed]
            else:
                tokens[i, 0] = req.last_token

        logits, self._cache = self._step_fn(
            self.params, self._cache, jnp.asarray(tokens),
            jnp.asarray(active))
        # ONE host transfer for all slots (the tunnel-safe pattern)
        logits_h = np.asarray(jax.device_get(logits))

        for i, req in enumerate(self._slots):
            if req is None:
                continue
            if req.consumed < len(req.prompt):
                req.consumed += 1
                if req.consumed < len(req.prompt):
                    continue  # still prefilling; logits not sampled yet
            tok = self._sample(logits_h[i])
            req.last_token = tok
            req.generated += 1
            req.emit(tok)
            self.stats["tokens_generated"] += 1
            if req.generated >= req.max_new_tokens or (
                    req.eos is not None and tok == req.eos):
                req.emit(None)
                self._slots[i] = None
        self.stats["steps"] += 1
        return True

    def _sample(self, logits: np.ndarray) -> int:
        if self.temperature <= 0.0:
            return int(np.argmax(logits))
        z = logits / self.temperature
        z = z - z.max()
        p = np.exp(z)
        p /= p.sum()
        return int(self._rng.choice(len(p), p=p))


class LLMDeployment:
    """Serve deployment: continuous-batching token streaming.

    Deploy with a concurrent replica so requests interleave::

        app = serve.deployment(
            LLMDeployment,
            ray_actor_options={"max_concurrency": 16},
        ).bind("llama-debug", max_slots=8, max_len=256)
        handle = serve.run(app, name="llm")
        for tok in handle.options(stream=True).remote([1, 2, 3], 16):
            ...

    Each ``__call__`` is a SYNC generator (the proven streaming-replica
    path); the engine advances on a dedicated background thread, so all
    concurrent callers share one jitted decode program and one KV cache.
    """

    def __init__(self, model="llama-debug", *, max_slots: int = 8,
                 max_len: int = 256, temperature: float = 0.0,
                 params=None, seed: int = 0):
        self.engine = LLMEngine(model, params, max_slots=max_slots,
                                max_len=max_len, temperature=temperature,
                                seed=seed)
        self._error: Optional[BaseException] = None
        self._wake = threading.Event()
        self._stop = False
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="llm-decode-loop")
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop:
            try:
                busy = self.engine.step()
            except BaseException as e:  # noqa: BLE001 - must not die silent
                # fail every outstanding request and surface via
                # check_health; the thread keeps running so a transient
                # backend error doesn't permanently kill the replica
                self._error = e
                self.engine.abort_all(e)
                self._wake.wait(timeout=1.0)
                self._wake.clear()
                continue
            if not busy:
                # idle: park until the next submit
                self._wake.wait(timeout=0.2)
                self._wake.clear()

    def __call__(self, prompt_tokens, max_new_tokens: int = 16,
                 eos: Optional[int] = None):
        from ray_tpu.util import tracing

        q: "queue.Queue[Any]" = queue.Queue()
        # manual spans (not span()): this is a generator — a thread-local
        # span context held across a yield would leak onto whatever the
        # worker thread runs next (graftlint tracing-context-capture).
        # queue = admission wait to the FIRST token (slot contention +
        # prefill); stream = the whole token stream — the per-request
        # latency decomposition SLO admission control needs (ISSUE 7).
        stream_span = tracing.manual_span(
            "serve.llm::stream", {"prompt_tokens": len(prompt_tokens),
                                  "max_new_tokens": max_new_tokens})
        queue_span = tracing.manual_span(
            "serve.llm::queue", {},
            parent=stream_span.traceparent if stream_span else None)
        req = None
        produced = 0
        try:
            # submit INSIDE the try: a dead engine must still finish the
            # admission span (it is the SLO signal for failed admission)
            req = self.engine.submit(prompt_tokens, max_new_tokens,
                                     q.put_nowait, eos=eos)
            self._wake.set()
            while True:
                try:
                    tok = q.get(timeout=120.0)
                except queue.Empty:
                    raise TimeoutError(
                        "llm decode loop produced no token for 120s"
                        + (f" (loop error: {self._error!r})"
                           if self._error else ""))
                if queue_span is not None:
                    queue_span.finish()
                    queue_span = None
                if tok is None:
                    return
                if isinstance(tok, BaseException):
                    raise RuntimeError(f"llm decode loop failed: {tok!r}")
                produced += 1
                yield tok
        finally:
            # client stopped consuming (disconnect / GC'd generator):
            # free the slot instead of generating into an orphan queue
            if req is not None:
                self.engine.cancel(req)
            if queue_span is not None:
                # failed/abandoned BEFORE the first token: the admission
                # wait still gets recorded (it is the SLO signal), marked
                # as never having produced
                queue_span.finish(error="no token produced")
            if stream_span is not None:
                stream_span.finish({"tokens": produced})

    def stats(self) -> Dict[str, Any]:
        return dict(self.engine.stats)

    def check_health(self) -> None:
        if not self._thread.is_alive():
            raise RuntimeError("llm decode loop thread died")
        if self._error is not None:
            raise RuntimeError(f"llm decode loop error: {self._error!r}")

    def __del__(self):  # pragma: no cover - GC-time best effort
        self._stop = True
