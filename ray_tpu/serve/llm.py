"""Continuous-batching LLM serving: paged slot engine + serve deployment.

Reference role: ``python/ray/serve/batching.py`` (request batching) +
streaming responses, joined into an LLM decode loop — the reference has
no LLM engine; this is the TPU-first differentiator (CLAUDE.md round-5
note). Design follows Orca-style token-level continuous batching over a
vLLM-style paged KV cache (PAPERS.md: the Gemma-on-TPU serving
comparison shows paged KV + batching policy, not raw FLOPs, decide TPU
serving throughput):

- The engine owns ONE jitted step (:func:`decode_step_paged`) over a
  fixed slot grid [max_slots, prefill_chunk]: static shapes, compiled
  once. Each iteration a decoding slot advances one token while a
  prefilling slot consumes up to ``prefill_chunk`` prompt tokens — so a
  long prompt drains in L/chunk steps WITHOUT stalling the decodes
  sharing its batch (the chunked-prefill TODO from the dense engine).
- KV lives in a block-paged pool (``serve/kv_cache.py`` +
  ``models.init_cache_paged``): admission claims BLOCKS, not slots, and
  a hash-trie prefix cache maps shared system prompts to shared
  immutable blocks — a prefix hit skips that prefill compute entirely
  (``pos`` starts past the reused tokens). Copy-on-write covers the one
  mutable case (a capped match reusing a partial tail block).
- An :class:`~ray_tpu.serve.admission.AdmissionController` sheds
  requests whose projected TTFT/decode rate would breach the declared
  :class:`~ray_tpu.serve.admission.SLOConfig`; per-request
  ``deadline_s`` is enforced across admission queueing AND streaming.
- ``paged=False`` keeps the dense per-slot cache
  (:func:`decode_step_multi`) — the same-container A/B baseline
  ``bench.py``'s ``serve_llm`` section measures against.
- The engine is serve-independent (testable standalone); the
  :class:`LLMDeployment` wrapper runs it on a background thread inside a
  ``max_concurrency`` replica and streams tokens to each caller through
  the ordinary streaming-generator path.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ray_tpu.serve.admission import (AdmissionController,
                                     DeadlineExceededError, RequestShedError,
                                     SLOConfig)
from ray_tpu.serve.kv_cache import BlockPool, PrefixCache


def _next_pow2(n: int) -> int:
    return 1 << max(n - 1, 0).bit_length()


#: sentinel distinct from None (None IS a stream terminal)
_NO_ITEM = object()


@dataclass(eq=False)   # identity semantics: generated __eq__ would
class _Request:        # elementwise-compare the prompt arrays and raise
    prompt: np.ndarray                 # [P] int32
    max_new_tokens: int
    # token sink: int token, None = done, Exception = engine failure
    emit: Callable[[Any], None]
    consumed: int = 0                  # prompt tokens fed so far
    generated: int = 0
    last_token: int = 0
    eos: Optional[int] = None
    cancelled: bool = False
    # paged-cache state (engine-owned)
    table: List[int] = field(default_factory=list)   # physical block ids
    pos: int = 0                       # KV tokens cached (incl. shared)
    # latency bookkeeping (TTFT/TPOT + deadline enforcement)
    submit_ts: float = 0.0             # monotonic
    deadline: Optional[float] = None   # monotonic absolute
    last_emit_ts: Optional[float] = None
    # disaggregated prefill/decode (ISSUE 13)
    prefill_only: bool = False         # stop after the first token and
    #                                    emit a KVExport instead of it
    adopt_kv: Optional[Dict[str, np.ndarray]] = None  # shipped prompt KV
    #                                    to scatter into claimed blocks
    # every sampled token, in order (elastic migration, r20): a live
    # session's continuation prompt on another replica is
    # prompt + gen_tokens[:-1] — the fed-token transcript the cached KV
    # positions actually correspond to. The trie insert on release keys
    # only the true prompt prefix, so this list is what keeps a migrated
    # session's adoption honest about token VALUES, not just counts.
    gen_tokens: List[int] = field(default_factory=list)


@dataclass(eq=False)
class KVExport:
    """What a prefill-only request emits instead of its first token: the
    sampled token plus the prompt's KV blocks gathered off the paged
    pool ([L, n_blocks, bs, kvh, hd] per tensor, host-side) — exactly
    the payload a decode engine's :meth:`LLMEngine.adopt` consumes."""

    token: int
    prompt_len: int
    block_size: int
    kv: Dict[str, np.ndarray]

    @property
    def nbytes(self) -> int:
        return sum(int(a.nbytes) for a in self.kv.values())


class LLMEngine:
    """Slot-based continuous-batching decode engine over one model.

    ``submit`` is thread-safe; ``step`` must be called from ONE driver
    thread (the deployment's loop thread) and returns whether any work
    remains. Greedy sampling by default; ``temperature`` > 0 samples.
    """

    def __init__(self, config, params=None, *, max_slots: int = 8,
                 max_len: int = 256, temperature: float = 0.0,
                 seed: int = 0, paged: bool = True,
                 block_size: Optional[int] = None,
                 num_blocks: Optional[int] = None,
                 prefill_chunk: Optional[int] = None,
                 prefix_cache: bool = True,
                 slo: Optional[SLOConfig] = None,
                 role: str = "colocated"):
        import jax
        import jax.numpy as jnp

        from ray_tpu import config as _knobs
        from ray_tpu import models

        if isinstance(config, str):
            config = models.get_config(config)
        self.config = config
        self.max_slots = max_slots
        self.max_len = max_len
        self.temperature = temperature
        self.paged = bool(paged)
        if role not in ("colocated", "prefill", "decode"):
            raise ValueError(f"unknown engine role {role!r}")
        self.role = role
        if params is None:
            params = models.init_params(jax.random.PRNGKey(seed), config)
        self.params = params
        # model multiplexing (serve/multiplex.py): a registry-managed
        # engine's params can be PAGED OUT between steps (dropped to the
        # arena store under budget pressure) and re-acquired lazily —
        # the provider is called at the top of step() when params are
        # absent. jit-safe: the step donates only the cache, so swapping
        # the params pytree never invalidates the compiled program.
        self.params_provider: Optional[Callable[[], Any]] = None
        if self.paged:
            bs = int(block_size or _knobs.get("llm_block_size"))
            self._tbl_width = -(-max_len // bs)
            nb = int(num_blocks or max_slots * self._tbl_width)
            self.pool = BlockPool(nb, bs)
            self.prefix = PrefixCache(self.pool) if prefix_cache else None
            self.prefill_chunk = max(
                1, int(prefill_chunk or _knobs.get("llm_prefill_chunk")))
            self._cache = models.init_cache_paged(config, nb, bs)
            # donate the cache: without donation every step/copy keeps
            # BOTH pool-sized buffers live (the old one is overwritten
            # immediately), doubling transient HBM for the KV pool —
            # fatal at real pool sizes on a 16 GB v5e. CPU ignores
            # donation (a one-time warning), so tests are unaffected.
            from ray_tpu.util.device_plane import registered_jit

            self._step_fn = registered_jit(self._raw_step_paged,
                                           name="serve::decode_step_paged",
                                           component="serve",
                                           donate_argnums=(1,))
            self._copy_fn = registered_jit(self._raw_copy,
                                           name="serve::copy_kv_block",
                                           component="serve",
                                           donate_argnums=(0,))
            # disaggregation (ISSUE 13): gather exports a request's
            # blocks (no donation — the pool stays live), scatter adopts
            # a shipped batch (donated — the old pool is dead on write).
            # Distinct block counts retrace; table widths bound the set.
            self._gather_fn = registered_jit(self._raw_gather,
                                             name="serve::gather_kv_blocks",
                                             component="serve")
            self._scatter_fn = registered_jit(self._raw_scatter,
                                              name="serve::scatter_kv_blocks",
                                              component="serve",
                                              donate_argnums=(0,))
            # warm the COW copy's compile NOW, not in the middle of the
            # first prefix-sharing request's admission (block 0 onto
            # itself over an all-zero cache is a no-op; src/dst trace as
            # scalars so one compile serves all)
            self._cache = self._copy_fn(self._cache, 0, 0)
        else:
            self.pool = None
            self.prefix = None
            self.prefill_chunk = 1
            self._cache = models.init_cache_multi(config, max_slots, max_len)
            from ray_tpu.util.device_plane import registered_jit

            self._step_fn = registered_jit(self._raw_step,
                                           name="serve::decode_step",
                                           component="serve")
        self.admission = AdmissionController(slo)
        self._rng = np.random.default_rng(seed)
        self._lock = threading.Lock()
        self._pending: List[_Request] = []
        self._slots: List[Optional[_Request]] = [None] * max_slots
        # live-session migration intake (elastic serving, r20): the
        # drain thread marks sessions here; the loop thread exports them
        # at the top of the next step (the cache is donation-aliased, so
        # only the step thread may gather from it)
        self._migrations: List[tuple] = []
        self.stats = {"steps": 0, "tokens_generated": 0,
                      "max_concurrent": 0, "requests": 0,
                      "prefix_hit_tokens": 0, "deadline_drops": 0,
                      "exported": 0, "adopted": 0, "migrated_out": 0}
        self._metrics = self._init_metrics()

    @staticmethod
    def _init_metrics():
        """Serving-tier built-ins (metric_defs-only creation). Instances
        are cached here so the hot loop never re-resolves the registry."""
        try:
            from ray_tpu.util import metric_defs as md

            return {
                "kv_free": md.get("rtpu_serve_kv_blocks_free"),
                "kv_used": md.get("rtpu_serve_kv_blocks_used"),
                "hits": md.get("rtpu_serve_prefix_cache_hits_total"),
                "misses": md.get("rtpu_serve_prefix_cache_misses_total"),
                "hit_tokens": md.get("rtpu_serve_prefix_hit_tokens_total"),
                "sheds": md.get("rtpu_serve_admission_sheds_total"),
                "ttft": md.get("rtpu_serve_ttft_seconds"),
                "tpot": md.get("rtpu_serve_tpot_seconds"),
                "pool_inflight": md.get("rtpu_serve_pool_inflight"),
                "pool_queued": md.get("rtpu_serve_pool_queued"),
                "pool_kv_used_frac":
                    md.get("rtpu_serve_pool_kv_used_fraction"),
                "achieved_flops":
                    md.get("rtpu_device_achieved_flops_per_s"),
            }
        except Exception:  # metrics plane unavailable (bare unit tests)
            return None

    def _raw_step(self, params, cache, tokens, active):
        from ray_tpu.models import decode_step_multi

        return decode_step_multi(params, cache, tokens, self.config,
                                 active=active)

    def _raw_step_paged(self, params, cache, tokens, tables, pos, nvalid,
                        active):
        from ray_tpu.models import decode_step_paged

        return decode_step_paged(params, cache, tokens, tables, pos,
                                 nvalid, self.config, active=active)

    @staticmethod
    def _raw_copy(cache, src, dst):
        from ray_tpu.models import copy_kv_block

        return copy_kv_block(cache, src, dst)

    @staticmethod
    def _raw_gather(cache, ids):
        from ray_tpu.models import gather_kv_blocks

        return gather_kv_blocks(cache, ids)

    @staticmethod
    def _raw_scatter(cache, ids, kv):
        from ray_tpu.models import scatter_kv_blocks

        return scatter_kv_blocks(cache, ids, kv)

    # -- thread-safe intake ------------------------------------------------

    def submit(self, prompt, max_new_tokens: int,
               emit: Callable[[Any], None],
               eos: Optional[int] = None,
               deadline_s: Optional[float] = None,
               prefill_only: bool = False) -> "_Request":
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prefill_only:
            if not self.paged:
                raise ValueError("prefill_only requires a paged engine "
                                 "(KV export is block-granular)")
            # the export happens at the FIRST sample: exactly one token
            # is produced here; the decode pool owns the rest
            max_new_tokens = 1
        if len(prompt) == 0:
            raise ValueError("empty prompt")
        if len(prompt) + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens "
                f"({max_new_tokens}) exceeds the engine's max_len "
                f"({self.max_len})")
        if self.paged:
            # a prefill-only request claims PROMPT blocks only: its one
            # sampled token's KV is never written (KV lands when a token
            # is FED, and feeding moves to the decode pool)
            width = self.pool.blocks_for_tokens(
                len(prompt) + (0 if prefill_only else max_new_tokens))
            if width > self.pool.num_blocks:
                # bigger than the WHOLE pool: it could never be admitted
                # — queueing it would pin the strict-FIFO head forever
                # and busy-spin the decode loop with zero active slots
                raise ValueError(
                    f"request needs {width} KV blocks but the pool has "
                    f"only {self.pool.num_blocks} total; raise "
                    f"num_blocks or lower max_new_tokens")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        # SLO gate BEFORE the request joins the queue: a doomed request
        # gets a fast RequestShedError, not a slow timeout
        with self._lock:
            queued = len(self._pending)
            queued_tokens = sum(len(r.prompt) for r in self._pending)
            free_slots = sum(r is None for r in self._slots)
        try:
            self.admission.check_admit(
                len(prompt), queued, queued_tokens, self.prefill_chunk,
                free_slots, self.max_slots - free_slots,
                deadline_s=deadline_s)
        except RequestShedError as e:
            if self._metrics:
                self._metrics["sheds"].inc(tags={"reason": e.reason})
            raise
        now = time.monotonic()
        req = _Request(prompt, max_new_tokens, emit, eos=eos,
                       submit_ts=now,
                       deadline=(now + deadline_s
                                 if deadline_s is not None else None),
                       prefill_only=prefill_only)
        with self._lock:
            self._pending.append(req)
            self.stats["requests"] += 1
        return req

    def adopt(self, prompt, kv: Dict[str, np.ndarray], first_token: int,
              max_new_tokens: int, emit: Callable[[Any], None],
              eos: Optional[int] = None,
              deadline_s: Optional[float] = None) -> "_Request":
        """Admit a request whose prompt KV was prefilled on ANOTHER
        engine (the decode half of disaggregated serving): claim a full
        table, scatter the shipped block batch into it, and start
        decoding from ``first_token`` — no prompt tokens ever run
        through this engine's model. ``kv`` is the
        :class:`KVExport` payload ([L, n_blocks, bs, kvh, hd] per
        tensor); the first token is re-emitted here so the caller sees
        one uninterrupted stream."""
        if not self.paged:
            raise ValueError("adopt requires a paged engine")
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if len(prompt) == 0:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if len(prompt) + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens "
                f"({max_new_tokens}) exceeds the engine's max_len "
                f"({self.max_len})")
        need = self.pool.blocks_for_tokens(len(prompt))
        got = int(kv["k"].shape[1])
        if got != need:
            raise ValueError(
                f"KV payload carries {got} blocks but the prompt needs "
                f"{need} (block_size {self.pool.block_size})")
        if int(kv["k"].shape[2]) != self.pool.block_size:
            raise ValueError(
                f"KV payload block_size {int(kv['k'].shape[2])} != this "
                f"engine's {self.pool.block_size}")
        # FULL geometry check, both tensors, against this engine's cache
        # ([L, n, bs, kvh, hd]): per-role engine kwargs make mismatched
        # pool configs constructible, and a bad payload must fail THIS
        # request at adopt — not blow up the jitted scatter later on the
        # engine loop, where abort_all would kill every in-flight stream
        ck = self._cache["k"]
        want = (int(ck.shape[0]), got, int(ck.shape[2]),
                int(ck.shape[3]), int(ck.shape[4]))
        for name in ("k", "v"):
            if tuple(int(d) for d in kv[name].shape) != want:
                raise ValueError(
                    f"KV payload {name} shape "
                    f"{tuple(kv[name].shape)} does not match this "
                    f"engine's cache geometry {want} "
                    "(mismatched pool model configs?)")
        width = self.pool.blocks_for_tokens(len(prompt) + max_new_tokens)
        if width > self.pool.num_blocks:
            raise ValueError(
                f"request needs {width} KV blocks but the pool has "
                f"only {self.pool.num_blocks} total")
        # decode-side admission: no prefill cost (the blocks arrive
        # precomputed), so only the queue/TPOT gates carry signal
        with self._lock:
            queued = len(self._pending)
            queued_tokens = sum(len(r.prompt) for r in self._pending)
            free_slots = sum(r is None for r in self._slots)
        try:
            self.admission.check_admit(
                1, queued, queued_tokens, self.prefill_chunk, free_slots,
                self.max_slots - free_slots, deadline_s=deadline_s)
        except RequestShedError as e:
            if self._metrics:
                self._metrics["sheds"].inc(tags={"reason": e.reason})
            raise
        now = time.monotonic()
        req = _Request(prompt, max_new_tokens, emit, eos=eos,
                       submit_ts=now,
                       deadline=(now + deadline_s
                                 if deadline_s is not None else None))
        # the copy is load-bearing, not defensive: store-path payloads
        # arrive as zero-copy views into the object store, and the
        # scatter runs later on the engine loop — by then the caller's
        # descriptor (and its ref pin) may be gone
        req.adopt_kv = {"k": np.ascontiguousarray(kv["k"]),
                        "v": np.ascontiguousarray(kv["v"])}
        req.last_token = int(first_token)
        req.gen_tokens.append(int(first_token))
        with self._lock:
            self._pending.append(req)
            self.stats["requests"] += 1
            self.stats["adopted"] += 1
        return req

    # -- weight paging (model multiplexing) --------------------------------

    def set_params(self, params) -> None:
        """Install (swap in) a params pytree. Called from the step/loop
        thread between steps; safe because the jitted step donates the
        cache, never the params."""
        self.params = params

    def drop_params(self) -> None:
        """Page this engine's weights out. Only legal while the engine
        has no in-flight work (the registry's pin accounting guarantees
        it); the next step with work re-acquires via
        ``params_provider``."""
        self.params = None

    def _ensure_params(self) -> None:
        if self.params is None:
            if self.params_provider is None:
                raise RuntimeError(
                    "engine params paged out and no params_provider set")
            self.params = self.params_provider()

    def cancel(self, req: "_Request") -> None:
        """Abandon a request: pending entries are dropped immediately; an
        in-slot request frees its slot (and KV blocks) at the next step
        without emitting further tokens (client disconnect must not leave
        zombie slots)."""
        with self._lock:
            req.cancelled = True
            if req in self._pending:
                self._pending.remove(req)

    def abort_all(self, error: BaseException) -> None:
        """Fail every outstanding request (decode loop died)."""
        with self._lock:
            victims = [r for r in self._slots if r is not None]
            victims += self._pending
            self._pending.clear()
            self._slots = [None] * self.max_slots
        for r in victims:
            # under the lock: block/trie mutation must be invisible to a
            # concurrent kv_state()/load_state() walking the trie
            with self._lock:
                self._release_blocks(r, insert=False)
            try:
                r.emit(error)
            except Exception:
                pass

    # -- paged block accounting -------------------------------------------

    def _claim_blocks(self, req: _Request, pending_copies: list) -> bool:
        """Admission = claiming KV blocks. Prefix-match the prompt, then
        allocate the remainder of the request's table (prompt + budgeted
        new tokens, all up front — a request admitted here can never OOM
        the pool mid-decode). Falls back to trie eviction; False = not
        enough blocks, the request stays queued.

        Pure host-side bookkeeping (runs under the engine lock): a
        needed copy-on-write DEVICE copy is queued onto
        ``pending_copies`` for :meth:`_sweep_and_admit` to run after the
        lock drops — a tunnel-stalled device op must not freeze
        ``submit()``/``kv_state()`` behind the lock."""
        pool, trie = self.pool, self.prefix
        total = len(req.prompt) + (0 if req.prefill_only
                                   else req.max_new_tokens)
        width = pool.blocks_for_tokens(total)
        if req.adopt_kv is not None:
            # adoption: the payload IS the prompt KV — a trie match would
            # alias blocks the scatter must not overwrite, so claim all
            # fresh (the finished request still seeds the trie on release)
            fresh = pool.alloc(width)
            if fresh is None and trie is not None:
                trie.evict(width - pool.free_count)
                fresh = pool.alloc(width)
            if fresh is None:
                return False
            req.table = fresh
            req.pos = req.consumed = len(req.prompt)
            n_kv = int(req.adopt_kv["k"].shape[1])
            pending_copies.append(("adopt", req, fresh[:n_kv],
                                   req.adopt_kv))
            req.adopt_kv = None
            return True
        lookup_stats = trie.stats() if trie is not None else None
        blocks, matched, cow = (trie.match(req.prompt.tolist())
                                if trie is not None else ([], 0, None))
        fresh_needed = width - len(blocks)
        fresh = pool.alloc(fresh_needed)
        if fresh is None and trie is not None:
            trie.evict(fresh_needed - pool.free_count)
            fresh = pool.alloc(fresh_needed)
        def roll_back():
            pool.release_all(blocks)
            if cow is not None:
                pool.release(cow)
            # roll back the lookup accounting: this SAME request re-runs
            # the match on every step while it waits at the queue head —
            # counting each retry would overstate hit rate exactly in
            # the pool-pressure regime the paged A/B measures
            if lookup_stats is not None:
                trie.hits = lookup_stats["hits"]
                trie.misses = lookup_stats["misses"]
                trie.hit_tokens = lookup_stats["hit_tokens"]

        if fresh is None:
            roll_back()
            return False
        if cow is not None:
            # capped match reused part of a shared block: queue the
            # device copy into the request's first fresh block (the cow
            # ref stays held until the copy lands)
            pending_copies.append(("cow", req, cow, fresh[0]))
        req.table = blocks + fresh
        req.pos = req.consumed = matched
        self.stats["prefix_hit_tokens"] += matched
        return True

    def _release_blocks(self, req: _Request, *, insert: bool) -> None:
        """Return a request's KV blocks. ``insert``: first offer the
        fully-written full prompt blocks to the prefix trie (the trie
        retains what it adopts), so the NEXT request with this system
        prompt hits."""
        if not self.paged or not req.table:
            return
        if insert and self.prefix is not None:
            n_full = min(len(req.prompt), req.pos) // self.pool.block_size
            if n_full:
                self.prefix.insert(
                    req.prompt[:n_full * self.pool.block_size].tolist(),
                    req.table[:n_full])
        self.pool.release_all(req.table)
        req.table = []

    # -- driver-thread loop body ------------------------------------------

    def _reset_slot(self, i: int) -> None:
        import jax.numpy as jnp

        self._cache["pos"] = self._cache["pos"].at[i].set(jnp.int32(0))

    def _sweep_and_admit(self) -> tuple:
        """Free finished/cancelled/expired slots, then admit pending
        requests while a slot AND their KV blocks are available (strict
        FIFO — no head-of-line bypass, so admission order is fair)."""
        now = time.monotonic()
        expired: List[_Request] = []
        pending_copies: List[tuple] = []
        with self._lock:
            for i in range(self.max_slots):
                r = self._slots[i]
                if r is not None and r.cancelled:
                    self._release_blocks(r, insert=False)
                    self._slots[i] = None
                elif (r is not None and r.deadline is not None
                        and now > r.deadline):
                    self._release_blocks(r, insert=False)
                    self._slots[i] = None
                    expired.append(r)
            # deadline enforcement ACROSS admission queueing: a request
            # that expired while waiting never occupies a slot
            still = []
            for r in self._pending:
                if r.deadline is not None and now > r.deadline:
                    expired.append(r)
                else:
                    still.append(r)
            self._pending[:] = still
            for i in range(self.max_slots):
                if self._slots[i] is None and self._pending:
                    cand = self._pending[0]
                    if self.paged:
                        if not self._claim_blocks(cand, pending_copies):
                            break  # pool exhausted: stay queued
                    self._pending.pop(0)
                    self._slots[i] = cand
                    if not self.paged:
                        self._reset_slot(i)
            active_now = sum(r is not None for r in self._slots)
            self.stats["max_concurrent"] = max(
                self.stats["max_concurrent"], active_now)
            have_pending = bool(self._pending)
        for r in expired:
            self.stats["deadline_drops"] += 1
            try:
                r.emit(DeadlineExceededError(
                    f"request deadline elapsed after "
                    f"{now - r.submit_ts:.3f}s (generated "
                    f"{r.generated}/{r.max_new_tokens})"))
            except Exception:
                pass
        # COW copies and adoption scatters run AFTER the lock drops (the
        # axon tunnel can stall a device op for minutes; submit()/
        # kv_state() must stay responsive) but BEFORE the step consumes
        # the tables
        adopts = []
        for kind, req, *rest in pending_copies:
            if kind == "adopt":
                adopts.append((req, rest[0], rest[1]))
                continue
            (src, dst) = rest
            try:
                self._cache = self._copy_fn(self._cache, src, dst)
                with self._lock:
                    self.pool.release(src)
            except BaseException as e:
                # device error: un-claim THIS request and fail it
                # (its table is already published, so abort_all
                # would miss the cow ref); then let the loop's abort
                # path handle the rest of the engine state
                with self._lock:
                    self.pool.release(src)
                    self._release_blocks(req, insert=False)
                    for i, r in enumerate(self._slots):
                        if r is req:
                            self._slots[i] = None
                try:
                    req.emit(e)
                except Exception:
                    pass
                raise
        if adopts:
            self._apply_adoptions(adopts)
        return active_now, have_pending

    def _apply_adoptions(self, adopts: List[tuple]) -> None:
        """Scatter every pending adoption's shipped blocks in ONE device
        op (a burst of arrivals must cost the in-flight decodes one
        kernel, not K), then emit each request's prefill-side first
        token. Ids/payload pad to a power-of-two bucket (pad ids are
        out-of-range -> dropped by the scatter) so the jit retraces per
        bucket, not per batch geometry."""
        import jax.numpy as jnp

        ids: List[int] = []
        ks: List[np.ndarray] = []
        vs: List[np.ndarray] = []
        for _req, table_prefix, kv in adopts:
            ids.extend(table_prefix)
            ks.append(kv["k"])
            vs.append(kv["v"])
        k = ks[0] if len(ks) == 1 else np.concatenate(ks, axis=1)
        v = vs[0] if len(vs) == 1 else np.concatenate(vs, axis=1)
        pad = _next_pow2(len(ids)) - len(ids)
        if pad:
            ids = ids + [self.pool.num_blocks] * pad
            zk = np.zeros(k.shape[:1] + (pad,) + k.shape[2:], k.dtype)
            zv = np.zeros(v.shape[:1] + (pad,) + v.shape[2:], v.dtype)
            k = np.concatenate([k, zk], axis=1)
            v = np.concatenate([v, zv], axis=1)
        try:
            self._cache = self._scatter_fn(
                self._cache, jnp.asarray(np.asarray(ids, np.int32)),
                {"k": jnp.asarray(k), "v": jnp.asarray(v)})
        except BaseException as e:
            with self._lock:
                for req, _tp, _kv in adopts:
                    self._release_blocks(req, insert=False)
                    for i, r in enumerate(self._slots):
                        if r is req:
                            self._slots[i] = None
            for req, _tp, _kv in adopts:
                try:
                    req.emit(e)
                except Exception:
                    pass
            raise
        now = time.monotonic()
        for req, _tp, _kv in adopts:
            req.generated = 1
            self._observe_emit(req, now)
            req.emit(req.last_token)
            self.stats["tokens_generated"] += 1
            if req.generated >= req.max_new_tokens or (
                    req.eos is not None and req.last_token == req.eos):
                # degenerate single-token request: done at adoption
                with self._lock:
                    self._release_blocks(req, insert=True)
                    for i, r in enumerate(self._slots):
                        if r is req:
                            self._slots[i] = None
                req.emit(None)

    def step(self) -> bool:
        """Admit pending requests, advance every active slot (one decode
        token, or up to ``prefill_chunk`` prompt tokens), route new
        tokens to their requests. Returns True if any slot is active or
        requests are waiting."""
        import jax
        import jax.numpy as jnp

        self._process_migrations(jax, jnp)
        active_now, have_pending = self._sweep_and_admit()
        if active_now == 0:
            self._sample_gauges()
            return have_pending
        self._ensure_params()

        t0 = time.perf_counter()
        if self.paged:
            logits_h, nvalid = self._advance_paged(jax, jnp)
        else:
            logits_h, nvalid = self._advance_dense(jax, jnp)
        step_dt = time.perf_counter() - t0
        if self.stats["steps"] > 0:
            # skip the FIRST step: it includes the jit trace+compile
            # (seconds), and seeding the EWMA with it would make a
            # freshly booted SLO-armed replica shed the very burst that
            # scaled it up
            self.admission.observe_step(step_dt)
            self._note_device_step(step_dt)

        now = time.monotonic()
        for i, req in enumerate(self._slots):
            if req is None:
                continue
            if req.consumed < len(req.prompt):
                req.consumed += int(nvalid[i])
                if req.consumed < len(req.prompt):
                    continue  # still prefilling; logits not sampled yet
            tok = self._sample(logits_h[i])
            req.last_token = tok
            req.generated += 1
            req.gen_tokens.append(tok)
            self._observe_emit(req, now)
            if req.prefill_only:
                self._emit_prefill_export(i, req, tok, jax, jnp)
                continue
            req.emit(tok)
            self.stats["tokens_generated"] += 1
            if req.generated >= req.max_new_tokens or (
                    req.eos is not None and tok == req.eos):
                # lock: the trie insert mutates children dicts that a
                # concurrent kv_state()/load_state() may be iterating
                with self._lock:
                    self._release_blocks(req, insert=True)
                req.emit(None)
                self._slots[i] = None
        self.stats["steps"] += 1
        self._sample_gauges()
        return True

    def _note_device_step(self, dt: float) -> None:
        """Cost-model step attribution: achieved FLOP/s for this
        engine's registered step program, from its static cost analysis
        and the measured step wall time (already bounded by the
        logits ``device_get`` in ``_advance_*`` — never
        ``block_until_ready``). The step also lands as a trace span so
        decode cadence joins the Perfetto device track."""
        program = ("serve::decode_step_paged" if self.paged
                   else "serve::decode_step")
        try:
            from ray_tpu.util import device_plane

            flops = device_plane.program_flops_per_step(program)
            if flops and dt > 0:
                fps = flops / dt
                self.stats["flops_per_s"] = round(fps, 1)
                if self._metrics is not None:
                    self._metrics["achieved_flops"].set(
                        fps, tags={"program": program})
            from ray_tpu.util import tracing

            if tracing.tracing_enabled():
                end = time.time_ns()
                tracing.record_span(
                    "serve::step", end - int(dt * 1e9), end,
                    {"program": program,
                     **({"flops": flops} if flops else {})})
        except Exception:
            pass

    def _emit_prefill_export(self, i: int, req: _Request, tok: int,
                             jax, jnp) -> None:
        """Export INSTEAD of streaming: gather the prompt's blocks off
        the pool (one device op, one host transfer) and hand them to the
        sink with the sampled token; the blocks then release normally —
        full prompt blocks into the trie, so repeated system prompts
        prefill once even on a dedicated prefill pool. The id list is
        padded to a power-of-two bucket (repeating the last id — reads
        are harmless) so the gather retraces per BUCKET, not per block
        count: a mid-stream jit compile would stall every in-flight
        decode for hundreds of ms."""
        nb = self.pool.blocks_for_tokens(len(req.prompt))
        bucket = min(_next_pow2(nb), self._tbl_width)
        ids = req.table[:nb] + [req.table[nb - 1]] * (bucket - nb)
        kv_dev = self._gather_fn(
            self._cache, jnp.asarray(np.asarray(ids, np.int32)))
        kv_host = jax.device_get(kv_dev)
        self.stats["exported"] += 1
        req.emit(KVExport(
            token=tok, prompt_len=len(req.prompt),
            block_size=self.pool.block_size,
            kv={"k": np.asarray(kv_host["k"])[:, :nb],
                "v": np.asarray(kv_host["v"])[:, :nb]}))
        with self._lock:
            self._release_blocks(req, insert=True)
        req.emit(None)
        self._slots[i] = None

    # -- live-session migration (elastic serving, r20) ---------------------

    def begin_migration(self) -> List[tuple]:
        """Mark every live DECODING session for export off this engine.
        Returns ``[(request, reply_queue)]``; the loop thread services
        each entry at the top of its next step, putting either the
        export payload dict, ``None`` (the session finished on its own
        before the export ran — nothing left to migrate), or the
        exception that killed the export. Thread-safe; called by the
        deployment's drain path, NOT the loop thread.

        Only sessions past prefill with at least one sampled token
        qualify: a still-prefilling request has no consumer-visible
        progress worth shipping — re-prefilling it on another replica
        via the ordinary retry path costs the same compute as resuming
        a partial prefill would."""
        if not self.paged:
            raise ValueError("session migration requires a paged engine "
                             "(KV export is block-granular)")
        out: List[tuple] = []
        with self._lock:
            for r in self._slots:
                if (r is None or r.cancelled or r.prefill_only
                        or r.consumed < len(r.prompt)
                        or not r.gen_tokens):
                    continue
                reply: "queue.Queue[Any]" = queue.Queue()
                self._migrations.append((r, reply))
                out.append((r, reply))
        return out

    def _process_migrations(self, jax, jnp) -> None:
        """Service pending session exports on the loop thread (top of
        step, BEFORE the advance — the migrating slot must not decode a
        token its export would then miss)."""
        with self._lock:
            if not self._migrations:
                return
            batch, self._migrations = self._migrations, []
        for req, reply in batch:
            # the session may have finished/cancelled between the drain
            # thread's mark and this step (its blocks are already
            # released): nothing to migrate, consumer already got the
            # full stream
            with self._lock:
                gone = req.cancelled or req not in self._slots
            if gone:
                reply.put(None)
                continue
            try:
                reply.put(self._export_session(req, jax, jnp))
            except BaseException as e:  # noqa: BLE001 - ships to drain
                reply.put(e)

    def _export_session(self, req: _Request, jax, jnp) -> Dict[str, Any]:
        """Gather a live decoding session's cached KV ([L, nb, bs, kvh,
        hd] per tensor, positions 0..pos-1) and retire the slot. The
        cache covers exactly the FED tokens — prompt plus every sampled
        token except the newest (``last_token`` is sampled but not yet
        fed) — so the destination adopts with prompt=fed transcript,
        first_token=last_token, and decoding continues token-exact.
        Same power-of-two id bucketing as :meth:`_emit_prefill_export`
        (a mid-stream retrace would stall surviving decodes)."""
        nb = self.pool.blocks_for_tokens(req.pos)
        bucket = min(_next_pow2(nb), self._tbl_width)
        ids = req.table[:nb] + [req.table[nb - 1]] * (bucket - nb)
        kv_dev = self._gather_fn(
            self._cache, jnp.asarray(np.asarray(ids, np.int32)))
        kv_host = jax.device_get(kv_dev)
        fed = list(map(int, req.prompt)) + req.gen_tokens[:-1]
        with self._lock:
            self._release_blocks(req, insert=True)
            for i, r in enumerate(self._slots):
                if r is req:
                    self._slots[i] = None
        self.stats["migrated_out"] += 1
        return {
            "kv": {"k": np.asarray(kv_host["k"])[:, :nb],
                   "v": np.asarray(kv_host["v"])[:, :nb]},
            "fed_tokens": fed,
            "last_token": int(req.last_token),
            "pos": int(req.pos),
            "generated": int(req.generated),
            "max_new_tokens": int(req.max_new_tokens),
            "eos": req.eos,
            "block_size": self.pool.block_size,
        }

    def _advance_dense(self, jax, jnp):
        """Dense per-slot cache: every active slot advances exactly one
        token (the pre-paged engine, kept as the A/B baseline)."""
        tokens = np.zeros((self.max_slots, 1), np.int32)
        active = np.zeros(self.max_slots, bool)
        nvalid = np.zeros(self.max_slots, np.int32)
        for i, req in enumerate(self._slots):
            if req is None:
                continue
            active[i] = True
            nvalid[i] = 1
            if req.consumed < len(req.prompt):
                tokens[i, 0] = req.prompt[req.consumed]
            else:
                tokens[i, 0] = req.last_token
        logits, self._cache = self._step_fn(
            self.params, self._cache, jnp.asarray(tokens),
            jnp.asarray(active))
        # ONE host transfer for all slots (the tunnel-safe pattern)
        return np.asarray(jax.device_get(logits)), nvalid

    def _advance_paged(self, jax, jnp):
        """Paged cache: decoding slots feed 1 token, prefilling slots
        feed up to ``prefill_chunk`` prompt tokens — one compiled
        program, no decode stall behind long prompts."""
        C = self.prefill_chunk
        tokens = np.zeros((self.max_slots, C), np.int32)
        nvalid = np.zeros(self.max_slots, np.int32)
        active = np.zeros(self.max_slots, bool)
        pos = np.zeros(self.max_slots, np.int32)
        tables = np.zeros((self.max_slots, self._tbl_width), np.int32)
        for i, req in enumerate(self._slots):
            if req is None:
                continue
            active[i] = True
            pos[i] = req.pos
            tables[i, :len(req.table)] = req.table
            if req.consumed < len(req.prompt):
                n = min(C, len(req.prompt) - req.consumed)
                tokens[i, :n] = req.prompt[req.consumed:req.consumed + n]
                nvalid[i] = n
            else:
                tokens[i, 0] = req.last_token
                nvalid[i] = 1
        logits, self._cache = self._step_fn(
            self.params, self._cache, jnp.asarray(tokens),
            jnp.asarray(tables), jnp.asarray(pos), jnp.asarray(nvalid),
            jnp.asarray(active))
        for i, req in enumerate(self._slots):
            if req is not None:
                req.pos += int(nvalid[i])
        return np.asarray(jax.device_get(logits)), nvalid

    def _observe_emit(self, req: _Request, now: float) -> None:
        m = self._metrics
        if req.last_emit_ts is None:
            ttft = now - req.submit_ts
            self.admission.observe_ttft(ttft)
            if m:
                m["ttft"].observe(ttft)
        else:
            tpot = now - req.last_emit_ts
            self.admission.observe_tpot(tpot)
            if m:
                m["tpot"].observe(tpot)
        req.last_emit_ts = now

    _mirrored = ("hits", "misses", "hit_tokens")

    def _sample_gauges(self) -> None:
        m = self._metrics
        if not m:
            return
        role = {"role": self.role}
        with self._lock:
            m["pool_inflight"].set(
                sum(r is not None for r in self._slots), tags=role)
            m["pool_queued"].set(len(self._pending), tags=role)
        if self.pool is not None:
            m["kv_free"].set(self.pool.free_count)
            m["kv_used"].set(self.pool.used_count)
            m["pool_kv_used_frac"].set(
                self.pool.used_count / max(self.pool.num_blocks, 1),
                tags=role)
        if self.prefix is not None:
            # counters mirror the trie's totals via deltas
            cur = self.prefix.stats()
            prev = getattr(self, "_mirror_prev", None) or {}
            for k in self._mirrored:
                d = cur[k] - prev.get(k, 0)
                if d > 0:
                    m[k].inc(d)
            self._mirror_prev = {k: cur[k] for k in self._mirrored}

    def _sample(self, logits: np.ndarray) -> int:
        if self.temperature <= 0.0:
            return int(np.argmax(logits))
        z = logits / self.temperature
        z = z - z.max()
        p = np.exp(z)
        p /= p.sum()
        return int(self._rng.choice(len(p), p=p))

    # -- introspection (routing + tests) ----------------------------------

    def kv_state(self) -> Dict[str, Any]:
        """Routing/leak-audit snapshot: block accounting + prefix-cache
        + admission state, all host-side (no device sync)."""
        # ONE lock covers slots AND the pool/trie walk: every trie
        # mutation site (claim in _sweep_and_admit, the finish/abort
        # releases) holds the same lock, so the iteration below can
        # never see a children dict resize mid-walk
        with self._lock:
            out: Dict[str, Any] = {
                "paged": self.paged,
                "role": self.role,
                "inflight": sum(r is not None for r in self._slots),
                "queued": len(self._pending),
                "max_slots": self.max_slots,
            }
            if self.pool is not None:
                out.update(kv_total=self.pool.num_blocks,
                           kv_free=self.pool.free_count,
                           kv_used=self.pool.used_count,
                           block_size=self.pool.block_size)
            if self.prefix is not None:
                out["prefix"] = self.prefix.stats()
                # cluster-wide prefix affinity (serve/multiplex.py): the
                # top trie roots by hit-weight, published through load
                # reports so handles can route sessions sharing a system
                # prompt to the replica that already holds it
                try:
                    from ray_tpu import config as _knobs

                    top = int(_knobs.get("serve_prefix_digest_top"))
                except Exception:
                    top = 8
                out["prefix_digest"] = self.prefix.digest(top)
                # claimable = free + evictable-from-trie: the CAPACITY
                # signal (a warm replica's raw free count trends to ~0
                # because the trie retains every finished prompt — that
                # is cache value, not pressure)
                out["kv_claimable"] = (self.pool.free_count
                                       + self.prefix.evictable_count())
            elif self.pool is not None:
                out["kv_claimable"] = self.pool.free_count
        out["admission"] = self.admission.snapshot()
        return out


class LLMDeployment:
    """Serve deployment: continuous-batching token streaming.

    Deploy with a concurrent replica so requests interleave::

        app = serve.deployment(
            LLMDeployment,
            ray_actor_options={"max_concurrency": 16},
        ).bind("llama-debug", max_slots=8, max_len=256)
        handle = serve.run(app, name="llm")
        for tok in handle.options(stream=True).remote([1, 2, 3], 16):
            ...

    Each ``__call__`` is a SYNC generator (the proven streaming-replica
    path); the engine advances on a dedicated background thread, so all
    concurrent callers share one jitted decode program and one paged KV
    pool. ``slo`` (dict or :class:`SLOConfig`) arms admission shedding;
    per-request ``deadline_s`` bounds queueing AND streaming.
    """

    def __init__(self, model="llama-debug", *, max_slots: int = 8,
                 max_len: int = 256, temperature: float = 0.0,
                 params=None, seed: int = 0, paged: bool = True,
                 block_size: Optional[int] = None,
                 num_blocks: Optional[int] = None,
                 prefill_chunk: Optional[int] = None,
                 prefix_cache: bool = True,
                 slo: Optional[Any] = None,
                 role: str = "colocated",
                 stream_batch: int = 1):
        if isinstance(slo, dict):
            slo = SLOConfig(**slo)
        # stream_batch > 1 turns on micro-batched token delivery: each
        # streamed message carries a LIST of up to stream_batch tokens —
        # whatever the engine produced since the consumer last kept up.
        # The first token still ships the moment it exists (TTFT is
        # untouched); only messages the consumer was already lagging
        # behind coalesce. This is the 1M-request envelope knob: at high
        # request rates the per-token object/message cost dominates the
        # serving stack, and a lagging consumer turns N messages into 1.
        self._stream_batch = max(1, int(stream_batch))
        # advertised in load reports so handles can route by model
        # residency (serve/multiplex.py multiplexes several of these)
        self._model_id = model if isinstance(model, str) else "custom"
        self.engine = self._engine_factory(
            model, params, max_slots=max_slots, max_len=max_len,
            temperature=temperature, seed=seed, paged=paged,
            block_size=block_size, num_blocks=num_blocks,
            prefill_chunk=prefill_chunk, prefix_cache=prefix_cache,
            slo=slo, role=role)
        self._error: Optional[BaseException] = None
        self._wake = threading.Event()
        self._stop = False
        # disaggregation plumbing (ISSUE 13), all lazy: the transfer
        # plane only exists on replicas that actually ship/adopt blocks
        self._kv_sender = None
        self._kv_receiver = None
        self._xfer_lock = threading.Lock()
        self._ident: Optional[Dict[str, str]] = None
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="llm-decode-loop")
        self._thread.start()

    def _engine_factory(self, *args, **kw) -> LLMEngine:
        """Engine construction seam: subclasses swap the engine class
        (``serve/multiplex.py``'s speculative deployment) without
        re-plumbing the loop-thread/streaming machinery."""
        return LLMEngine(*args, **kw)

    def _loop(self) -> None:
        if self.engine.role == "prefill":
            # dedicated-decode-capacity analog for shared-core hosts:
            # the prefill pool's step loop yields the core to decode
            # cadence (see the serve_prefill_nice knob); on a real
            # accelerator the step blocks on the device, so this is free
            try:
                from ray_tpu import config as _knobs

                nice = int(_knobs.get("serve_prefill_nice"))
                if nice > 0:
                    os.setpriority(os.PRIO_PROCESS,
                                   threading.get_native_id(), nice)
            except Exception:
                pass
        while not self._stop:
            try:
                busy = self.engine.step()
            except BaseException as e:  # noqa: BLE001 - must not die silent
                # fail every outstanding request and surface via
                # check_health; the thread keeps running so a transient
                # backend error doesn't permanently kill the replica
                self._error = e
                self.engine.abort_all(e)
                self._wake.wait(timeout=1.0)
                self._wake.clear()
                continue
            if not busy:
                # idle: park until the next submit
                self._wake.wait(timeout=0.2)
                self._wake.clear()

    def __call__(self, prompt_tokens, max_new_tokens: int = 16,
                 eos: Optional[int] = None,
                 deadline_s: Optional[float] = None):
        q: "queue.Queue[Any]" = queue.Queue()

        def submit():
            return self.engine.submit(prompt_tokens, max_new_tokens,
                                      q.put_nowait, eos=eos,
                                      deadline_s=deadline_s)

        return self._token_stream(q, submit, len(prompt_tokens),
                                  max_new_tokens, deadline_s)

    def _token_stream(self, q: "queue.Queue[Any]", submit,
                      n_prompt: int, max_new_tokens: int,
                      deadline_s: Optional[float]):
        """The streaming body shared by the colocated request path and
        the decode pool's adopt path: run ``submit`` (engine intake),
        then drain the request's token queue to the caller."""
        from ray_tpu import config as _knobs
        from ray_tpu.util import tracing

        stall_timeout = float(_knobs.get("llm_stall_timeout_s"))
        deadline = (time.monotonic() + deadline_s
                    if deadline_s is not None else None)
        # manual spans (not span()): this is a generator — a thread-local
        # span context held across a yield would leak onto whatever the
        # worker thread runs next (graftlint tracing-context-capture).
        # queue = admission wait to the FIRST token (slot contention +
        # prefill); stream = the whole token stream — the per-request
        # latency decomposition SLO admission control needs (ISSUE 7).
        stream_span = tracing.manual_span(
            "serve.llm::stream", {"prompt_tokens": n_prompt,
                                  "max_new_tokens": max_new_tokens,
                                  "role": self.engine.role})
        queue_span = tracing.manual_span(
            "serve.llm::queue", {},
            parent=stream_span.traceparent if stream_span else None)
        req = None
        produced = 0
        try:
            # submit INSIDE the try: a dead engine must still finish the
            # admission span (it is the SLO signal for failed admission)
            req = submit()
            self._wake.set()
            while True:
                wait = stall_timeout
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise DeadlineExceededError(
                            f"request deadline ({deadline_s}s) elapsed "
                            f"after {produced} tokens")
                    wait = min(wait, remaining)
                try:
                    tok = q.get(timeout=wait)
                except queue.Empty:
                    if (deadline is not None
                            and time.monotonic() >= deadline):
                        raise DeadlineExceededError(
                            f"request deadline ({deadline_s}s) elapsed "
                            f"after {produced} tokens")
                    raise TimeoutError(
                        f"llm decode loop produced no token for "
                        f"{stall_timeout:.0f}s"
                        + (f" (loop error: {self._error!r})"
                           if self._error else ""))
                if queue_span is not None:
                    queue_span.finish()
                    queue_span = None
                if tok is None:
                    return
                if isinstance(tok, (DeadlineExceededError,
                                    RequestShedError)):
                    raise tok  # admission/deadline verdicts pass through
                if isinstance(tok, BaseException):
                    raise RuntimeError(f"llm decode loop failed: {tok!r}")
                if self._stream_batch == 1:
                    produced += 1
                    yield tok
                    continue
                # micro-batched delivery: sweep whatever else the engine
                # already produced (bounded by stream_batch) into this
                # message; a terminal item found mid-sweep is handled
                # AFTER the tokens before it reach the consumer
                chunk = [tok]
                terminal = _NO_ITEM
                while len(chunk) < self._stream_batch:
                    try:
                        nxt = q.get_nowait()
                    except queue.Empty:
                        break
                    if nxt is None or isinstance(nxt, BaseException):
                        terminal = nxt
                        break
                    chunk.append(nxt)
                produced += len(chunk)
                yield chunk
                if terminal is _NO_ITEM:
                    continue
                if terminal is None:
                    return
                if isinstance(terminal, (DeadlineExceededError,
                                         RequestShedError)):
                    raise terminal
                raise RuntimeError(
                    f"llm decode loop failed: {terminal!r}")
        finally:
            # client stopped consuming (disconnect / GC'd generator):
            # free the slot instead of generating into an orphan queue
            if req is not None:
                self.engine.cancel(req)
            if queue_span is not None:
                # failed/abandoned BEFORE the first token: the admission
                # wait still gets recorded (it is the SLO signal), marked
                # as never having produced
                queue_span.finish(error="no token produced")
            if stream_span is not None:
                stream_span.finish({"tokens": produced})

    # -- disaggregated prefill/decode (ISSUE 13) ---------------------------

    def identity(self) -> Dict[str, str]:
        """This replica's transfer identity: actor id (channel naming)
        + node id (channel-vs-store path choice). Cached — the runtime
        context is task-local, so capture happens on first request."""
        if self._ident is None or self._ident["actor"] is None:
            # actor id is TASK-context-local: calls arriving outside a
            # task (the load-report push thread) see None — keep retrying
            # until a real request captures it. Channel names derive from
            # it, so it must be the unique actor id, never a placeholder.
            try:
                import ray_tpu

                ctx = ray_tpu.get_runtime_context()
                self._ident = {"actor": ctx.get_actor_id(),
                               "node": ctx.get_node_id(),
                               "role": self.engine.role}
            except Exception:
                # no runtime at all (in-process engine A/B harness):
                # a stable per-process host identity still lets the
                # same-host channel path work
                import os

                self._ident = {"actor": None,
                               "node": os.environ.get("RTPU_NODE_ID",
                                                      "local"),
                               "role": self.engine.role}
        return self._ident

    def _max_payload_bytes(self) -> int:
        eng = self.engine
        c = eng._cache["k"]
        per_block = int(c.dtype.itemsize) * int(np.prod(c.shape[2:])) \
            * int(c.shape[0]) * 2
        return per_block * eng._tbl_width

    def prefill_export(self, prompt_tokens, transfer: Dict[str, Any],
                       deadline_s: Optional[float] = None
                       ) -> Dict[str, Any]:
        """Prefill-pool entry point: run chunked prefill, then ship the
        prompt's KV blocks toward the decode replica named by
        ``transfer`` ({req, dst, dst_node}) and return the transfer
        descriptor (+ first token in its meta). The payload moves over a
        DeviceChannel ring when both replicas share ``dst_node``'s host,
        else through the object store's chunk-parallel pull path."""
        from ray_tpu import config as _knobs
        from ray_tpu.serve.kv_transfer import KVSender

        stall_timeout = float(_knobs.get("llm_stall_timeout_s"))
        q: "queue.Queue[Any]" = queue.Queue()
        req = self.engine.submit(prompt_tokens, 1, q.put_nowait,
                                 deadline_s=deadline_s, prefill_only=True)
        self._wake.set()
        export = None
        try:
            wait = stall_timeout if deadline_s is None \
                else min(stall_timeout, deadline_s)
            while True:
                tok = q.get(timeout=wait)
                if isinstance(tok, KVExport):
                    export = tok
                    continue
                if tok is None:
                    break
                if isinstance(tok, BaseException):
                    raise tok
        except queue.Empty:
            raise TimeoutError(
                f"prefill produced no export for {wait:.0f}s"
                + (f" (loop error: {self._error!r})"
                   if self._error else ""))
        finally:
            self.engine.cancel(req)
        if export is None:
            raise RuntimeError("prefill finished without a KV export")
        with self._xfer_lock:
            if self._kv_sender is None:
                import uuid

                # actor id when deployed; a process-unique fallback for
                # the in-process harness (bench/replay A/B) — channel
                # names must never collide across senders on one host
                src = self.identity()["actor"] or uuid.uuid4().hex[:12]
                self._kv_sender = KVSender(
                    src, max_payload_bytes=self._max_payload_bytes())
        same_host = bool(transfer.get("dst_node")) and \
            transfer["dst_node"] == self.identity()["node"]
        return self._kv_sender.ship(
            export, req_id=transfer["req"], dst_id=transfer["dst"],
            same_host=same_host)

    def adopt_stream(self, prompt_tokens, desc: Dict[str, Any],
                     max_new_tokens: int = 16, eos: Optional[int] = None,
                     deadline_s: Optional[float] = None):
        """Decode-pool entry point: fetch the shipped KV-block batch
        named by ``desc``, adopt it into this engine's pool, and stream
        the tokens (the first one — sampled by prefill — included)."""
        from ray_tpu.serve.kv_transfer import KVReceiver

        with self._xfer_lock:
            if self._kv_receiver is None:
                self._kv_receiver = KVReceiver()
        q: "queue.Queue[Any]" = queue.Queue()

        def submit():
            timeout = 30.0 if deadline_s is None else min(30.0, deadline_s)
            meta, kv = self._kv_receiver.fetch(desc, timeout=timeout)
            return self.engine.adopt(prompt_tokens, kv, meta["token"],
                                     max_new_tokens, q.put_nowait,
                                     eos=eos, deadline_s=deadline_s)

        return self._token_stream(q, submit, len(prompt_tokens),
                                  max_new_tokens, deadline_s)

    # -- elastic drain: migrate live sessions instead of re-prefilling -----

    def drain_sessions(self, destinations: List[Dict[str, Any]],
                       timeout_s: float = 30.0) -> Dict[str, Any]:
        """Preemption drain (r20): ship every live decode session's KV
        blocks to a surviving replica over the ISSUE-13 transfer plane,
        then hand each session's stream a migration marker so the caller
        splices the continuation — no re-prefill, token-exact under
        greedy sampling. ``destinations`` is a round-robin candidate
        list of ``{"dst": actor_id_hex, "dst_node": node_id|None}``.

        The marker rides the ordinary token stream (a dict is not a
        token): :class:`~ray_tpu.serve.disagg.DisaggHandle` intercepts
        it, reconstructs the fed-token prompt from what it already
        yielded, and calls ``adopt_stream`` on the destination. The
        re-emitted handoff token (adoption re-emits ``first_token``) is
        deduped handle-side."""
        from ray_tpu.serve.kv_transfer import KVSender
        from ray_tpu.util import events

        if not destinations:
            raise ValueError("drain needs at least one destination "
                             "replica")
        pending = self.engine.begin_migration()
        self._wake.set()
        me = self.identity()["actor"] or ""
        try:
            events.emit("serve_drain", replica=me,
                        role=self.engine.role, sessions=len(pending),
                        destinations=len(destinations))
        except Exception:
            pass
        migrated, failed, finished = 0, 0, 0
        if pending:
            with self._xfer_lock:
                if self._kv_sender is None:
                    import uuid

                    src = me or uuid.uuid4().hex[:12]
                    self._kv_sender = KVSender(
                        src, max_payload_bytes=self._max_payload_bytes())
        for n, (req, reply) in enumerate(pending):
            dst = destinations[n % len(destinations)]
            try:
                payload = reply.get(timeout=timeout_s)
                if payload is None:
                    finished += 1   # completed on its own pre-export
                    continue
                if isinstance(payload, BaseException):
                    raise payload
                import uuid

                req_id = uuid.uuid4().hex
                same_host = bool(dst.get("dst_node")) and \
                    dst["dst_node"] == self.identity()["node"]
                desc = self._kv_sender.ship(
                    KVExport(token=payload["last_token"],
                             prompt_len=payload["pos"],
                             block_size=payload["block_size"],
                             kv=payload["kv"]),
                    req_id=req_id, dst_id=dst["dst"],
                    same_host=same_host)
                # budget: adoption re-emits the handoff token (deduped
                # by the handle), so the destination owes remaining+1
                req.emit({"__migrate__": {
                    "desc": desc, "dst": dst["dst"],
                    "prompt_tokens": payload["fed_tokens"],
                    "first_token": payload["last_token"],
                    "max_new_tokens": (payload["max_new_tokens"]
                                       - payload["generated"] + 1),
                    "eos": payload["eos"],
                }})
                req.emit(None)
                migrated += 1
                try:
                    events.emit("serve_session_migrated", replica=me,
                                dst=dst["dst"], req=req_id,
                                kv_tokens=payload["pos"],
                                generated=payload["generated"])
                except Exception:
                    pass
            except BaseException as e:  # noqa: BLE001 - per-session
                failed += 1
                try:
                    req.emit(e)
                except Exception:
                    pass
        return {"sessions": len(pending), "migrated": migrated,
                "failed": failed, "finished": finished}

    def stats(self) -> Dict[str, Any]:
        out = dict(self.engine.stats)
        out.update(self.engine.kv_state())
        return out

    def kv_state(self) -> Dict[str, Any]:
        return self.engine.kv_state()

    def load_state(self) -> Dict[str, Any]:
        """Load report the replica pushes to the controller (the routing
        + autoscaling signal). ``kv_free`` here is the CLAIMABLE count
        (free list + trie-evictable): prefix-cache retention is cache
        value, not pressure — reporting the raw free count would make a
        warm idle replica read ~100% utilized, steering traffic to cold
        replicas and driving autoscale runaway."""
        s = self.engine.kv_state()
        return {"inflight": s["inflight"] + s["queued"],
                # model-residency + prefix-affinity routing signals
                # (ISSUE 16): which models this replica can serve without
                # a swap-in, and the hottest cached system prompts
                "models": {self._model_id: {
                    "state": "hbm",
                    "inflight": s["inflight"] + s["queued"]}},
                "prefix_digest": s.get("prefix_digest", []),
                "kv_free": s.get("kv_claimable", s.get("kv_free", 0)),
                "kv_total": s.get("kv_total", 0),
                # disaggregation routing signals (ISSUE 13): pool role,
                # host identity for channel-vs-store transfer choice,
                # and queue depth for prefill-capacity picking
                "role": s.get("role", "colocated"),
                "node": self.identity()["node"],
                "actor": self.identity()["actor"],
                "queued": s["queued"],
                "max_slots": s["max_slots"],
                "block_size": s.get("block_size", 0)}

    def check_health(self) -> None:
        if not self._thread.is_alive():
            raise RuntimeError("llm decode loop thread died")
        if self._error is not None:
            raise RuntimeError(f"llm decode loop error: {self._error!r}")

    def close(self) -> None:
        """Stop the step loop and unlink/close the KV-transfer planes.
        In-process harnesses (bench A/Bs) MUST call this: outside a
        runtime the rings carry the unswept ``nosess`` session prefix,
        so GC-time ``__del__`` is the only other thing standing between
        a ring and a leaked /dev/shm segment."""
        self._stop = True
        with self._xfer_lock:
            planes, self._kv_sender, self._kv_receiver = (
                (self._kv_sender, self._kv_receiver), None, None)
        for plane in planes:
            if plane is not None:
                try:
                    plane.close()
                except Exception:
                    pass

    def __del__(self):  # pragma: no cover - GC-time best effort
        self.close()
