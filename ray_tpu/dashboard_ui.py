"""Single-page dashboard UI served at ``/`` (reference ``dashboard/client``
role, deliberately dependency-free: one static HTML page that polls the
JSON endpoints and renders cluster state tables — nodes, actors, tasks,
objects, placement groups, serve applications — plus the raw /metrics
link. The reference ships a 21.9k-LoC React SPA; the equivalent operator
value here is live tabular state, which this page delivers without a
build toolchain)."""

INDEX_HTML = """<!DOCTYPE html>
<html>
<head>
<meta charset="utf-8">
<title>ray_tpu dashboard</title>
<style>
  body { font-family: -apple-system, 'Segoe UI', Roboto, sans-serif;
         margin: 0; background: #f6f7f9; color: #1a1d21; }
  header { background: #1a1d21; color: #fff; padding: 10px 20px;
           display: flex; align-items: baseline; gap: 14px; }
  header h1 { font-size: 16px; margin: 0; }
  header span { color: #9aa3ad; font-size: 12px; }
  nav { padding: 8px 20px; background: #fff; border-bottom: 1px solid #e3e6ea; }
  nav a { margin-right: 12px; cursor: pointer; color: #2563eb;
          text-decoration: none; font-size: 13px; }
  nav a.active { font-weight: 600; border-bottom: 2px solid #2563eb; }
  main { padding: 16px 20px; }
  table { border-collapse: collapse; width: 100%; background: #fff;
          font-size: 12.5px; }
  th, td { text-align: left; padding: 6px 10px;
           border-bottom: 1px solid #eceff3; }
  th { background: #f0f2f5; font-weight: 600; position: sticky; top: 0; }
  .pill { padding: 1px 8px; border-radius: 9px; font-size: 11px; }
  .ALIVE, .READY, .FINISHED, .RUNNING { background:#e7f6ec; color:#16803c; }
  .DEAD, .ERROR, .FAILED { background: #fdeaea; color: #b42318; }
  .PENDING, .RESTARTING { background: #fff4e5; color: #b25e09; }
  #err { color: #b42318; font-size: 12px; padding: 4px 20px; }
</style>
</head>
<body>
<header><h1>ray_tpu</h1><span id="ts"></span>
  <span style="margin-left:auto"><a href="/metrics"
    style="color:#9aa3ad">/metrics</a></span></header>
<nav id="nav"></nav>
<div id="err"></div>
<main><table id="tbl"><thead></thead><tbody></tbody></table></main>
<script>
const TABS = {
  nodes: "/api/nodes", actors: "/api/actors", tasks: "/api/tasks",
  objects: "/api/objects", workers: "/api/workers",
  placement_groups: "/api/placement_groups",
  serve: "/api/serve/applications",
};
let current = "nodes";
const nav = document.getElementById("nav");
for (const name of Object.keys(TABS)) {
  const a = document.createElement("a");
  a.textContent = name; a.id = "tab-" + name;
  a.onclick = () => { current = name; refresh(); };
  nav.appendChild(a);
}
function esc(s) {
  // cluster-provided strings (actor/task names come from user code) must
  // never reach innerHTML unescaped — stored-XSS guard
  return String(s).replace(/[&<>"']/g, c => ({
    "&": "&amp;", "<": "&lt;", ">": "&gt;",
    '"': "&quot;", "'": "&#39;"})[c]);
}
function cell(v) {
  if (v === null || v === undefined) return "";
  if (typeof v === "object") return esc(JSON.stringify(v));
  return esc(v);
}
function statePill(v) {
  const s = esc(v);
  const cls = /^[A-Za-z_]+$/.test(String(v)) ? String(v) : "";
  return `<span class="pill ${cls}">${s}</span>`;
}
async function refresh() {
  for (const n of Object.keys(TABS))
    document.getElementById("tab-" + n)
      .classList.toggle("active", n === current);
  try {
    const resp = await fetch(TABS[current]);
    const data = (await resp.json()).result;
    let rows = Array.isArray(data) ? data
      : (data && data.applications
         ? Object.entries(data.applications).map(
             ([k, v]) => ({name: k, ...v}))
         : Object.entries(data || {}).map(([k, v]) => ({key: k, ...v})));
    const thead = document.querySelector("#tbl thead");
    const tbody = document.querySelector("#tbl tbody");
    if (!rows.length) { thead.innerHTML = "<tr><th>(empty)</th></tr>";
                        tbody.innerHTML = ""; }
    else {
      const cols = Object.keys(rows[0]);
      thead.innerHTML = "<tr>" + cols.map(c => `<th>${esc(c)}</th>`).join("")
                        + "</tr>";
      tbody.innerHTML = rows.map(r => "<tr>" + cols.map(c => {
        const v = r[c];
        const isState = ["state", "status", "Alive", "alive"].includes(c);
        return `<td>${isState ? statePill(v) : cell(v)}</td>`;
      }).join("") + "</tr>").join("");
    }
    document.getElementById("ts").textContent =
      "updated " + new Date().toLocaleTimeString();
    document.getElementById("err").textContent = "";
  } catch (e) {
    document.getElementById("err").textContent = "fetch failed: " + e;
  }
}
refresh();
setInterval(refresh, 3000);
</script>
</body>
</html>
"""
