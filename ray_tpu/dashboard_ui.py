"""Single-page dashboard UI served at ``/`` (reference ``dashboard/client``
role, deliberately dependency-free: one static HTML page that polls the
JSON endpoints).

Views (reference SPA feature -> here):

- live state tables (nodes/actors/tasks/objects/workers/PGs/serve) with
  **row drill-down**: click a row for the full record as pretty JSON in a
  side panel (reference actor/task detail pages);
- **timeline**: per-worker swimlanes of task-execution spans rendered
  from ``/api/timeline`` (the Chrome-trace events ``ray_tpu timeline``
  exports), hover for name/duration (reference timeline view);
- **metrics**: sparkline history + current value for key gauges polled
  from ``/metrics`` (Prometheus text parsed client-side), plus the full
  sample table (reference Grafana-panel role, minus Grafana).

The reference ships a 21.9k-LoC React SPA; the operator value is live
state + drill-down + a timeline + metric trends, which this page delivers
without a build toolchain. Colors follow the repo-wide dataviz palette
(series hues for identity, status pills for state, light+dark via
``prefers-color-scheme``).
"""

INDEX_HTML = """<!DOCTYPE html>
<html>
<head>
<meta charset="utf-8">
<title>ray_tpu dashboard</title>
<style>
  :root {
    color-scheme: light;
    --surface-1: #fcfcfb; --surface-2: #ffffff; --border: #e3e6ea;
    --text-primary: #0b0b0b; --text-secondary: #52514e;
    --text-muted: #8a8985;
    --series-1: #2a78d6; --series-2: #eb6834; --series-3: #1baf7a;
    --series-other: #9aa3ad;
    --ok-bg: #e7f6ec; --ok-fg: #16803c;
    --bad-bg: #fdeaea; --bad-fg: #b42318;
    --warn-bg: #fff4e5; --warn-fg: #b25e09;
    --header-bg: #1a1d21; --header-fg: #ffffff;
  }
  @media (prefers-color-scheme: dark) {
    :root {
      color-scheme: dark;
      --surface-1: #1a1a19; --surface-2: #242423; --border: #3a3a38;
      --text-primary: #ffffff; --text-secondary: #c3c2b7;
      --text-muted: #8a8985;
      --series-1: #3987e5; --series-2: #d95926; --series-3: #199e70;
      --series-other: #6a6a68;
      --ok-bg: #10331d; --ok-fg: #69d391;
      --bad-bg: #3d1513; --bad-fg: #f1968f;
      --warn-bg: #3a2a10; --warn-fg: #eec07a;
      --header-bg: #0b0b0b; --header-fg: #ffffff;
    }
  }
  body { font-family: -apple-system, 'Segoe UI', Roboto, sans-serif;
         margin: 0; background: var(--surface-1);
         color: var(--text-primary); }
  header { background: var(--header-bg); color: var(--header-fg);
           padding: 10px 20px; display: flex; align-items: baseline;
           gap: 14px; }
  header h1 { font-size: 16px; margin: 0; }
  header span { color: var(--text-muted); font-size: 12px; }
  nav { padding: 8px 20px; background: var(--surface-2);
        border-bottom: 1px solid var(--border); }
  nav a { margin-right: 12px; cursor: pointer; color: var(--series-1);
          text-decoration: none; font-size: 13px; }
  nav a.active { font-weight: 600;
                 border-bottom: 2px solid var(--series-1); }
  main { padding: 16px 20px; display: flex; gap: 16px;
         align-items: flex-start; }
  #content { flex: 1 1 auto; min-width: 0; }
  table { border-collapse: collapse; width: 100%;
          background: var(--surface-2); font-size: 12.5px; }
  th, td { text-align: left; padding: 6px 10px;
           border-bottom: 1px solid var(--border); }
  th { background: var(--surface-1); font-weight: 600;
       position: sticky; top: 0; color: var(--text-secondary); }
  tbody tr { cursor: pointer; }
  tbody tr:hover { background: color-mix(in srgb, var(--series-1) 8%,
                                         var(--surface-2)); }
  .pill { padding: 1px 8px; border-radius: 9px; font-size: 11px; }
  .ALIVE, .READY, .FINISHED, .RUNNING, .HEALTHY
    { background: var(--ok-bg); color: var(--ok-fg); }
  .DEAD, .ERROR, .FAILED, .UNHEALTHY
    { background: var(--bad-bg); color: var(--bad-fg); }
  .PENDING, .RESTARTING, .DEPLOYING
    { background: var(--warn-bg); color: var(--warn-fg); }
  #err { color: var(--bad-fg); font-size: 12px; padding: 4px 20px; }
  #detail { flex: 0 0 380px; max-width: 380px; background:
            var(--surface-2); border: 1px solid var(--border);
            border-radius: 6px; padding: 10px 12px; display: none;
            position: sticky; top: 10px; }
  #detail h2 { font-size: 13px; margin: 0 0 6px;
               color: var(--text-secondary); display: flex; }
  #detail h2 a { margin-left: auto; cursor: pointer; font-weight: 400;
                 color: var(--text-muted); text-decoration: none; }
  #detail pre { font-size: 11.5px; white-space: pre-wrap;
                word-break: break-all; margin: 0; max-height: 70vh;
                overflow: auto; color: var(--text-primary); }
  svg text { fill: var(--text-secondary); font-size: 10.5px; }
  .lane-label { fill: var(--text-muted); }
  .axis line { stroke: var(--border); }
  #tooltip { position: fixed; pointer-events: none; display: none;
             background: var(--surface-2); color: var(--text-primary);
             border: 1px solid var(--border); border-radius: 4px;
             padding: 4px 8px; font-size: 11.5px; z-index: 10;
             box-shadow: 0 2px 8px rgba(0,0,0,.15); }
  .mcards { display: grid; gap: 12px;
            grid-template-columns: repeat(auto-fill, minmax(240px, 1fr));
            margin-bottom: 16px; }
  .mcard { background: var(--surface-2); border: 1px solid var(--border);
           border-radius: 6px; padding: 10px 12px; }
  .mcard .name { font-size: 11.5px; color: var(--text-secondary); }
  .mcard .val { font-size: 20px; font-weight: 600; margin: 2px 0 6px; }
  .note { font-size: 11.5px; color: var(--text-muted); margin: 8px 0; }
</style>
</head>
<body>
<header><h1>ray_tpu</h1><span id="ts"></span>
  <span style="margin-left:auto"><a href="/metrics"
    style="color:var(--text-muted)">/metrics</a></span></header>
<nav id="nav"></nav>
<div id="err"></div>
<main>
  <div id="content">
    <table id="tbl"><thead></thead><tbody></tbody></table>
    <div id="special"></div>
  </div>
  <div id="detail"><h2><span id="dtitle"></span>
    <a id="dclose">close</a></h2><pre id="djson"></pre></div>
</main>
<div id="tooltip"></div>
<script>
const TABS = {
  nodes: "/api/nodes", actors: "/api/actors", tasks: "/api/tasks",
  objects: "/api/objects", workers: "/api/workers",
  placement_groups: "/api/placement_groups",
  jobs: "/api/jobs",
  serve: "/api/serve/applications",
  timeline: null, metrics: null, contention: null,
};
let current = "nodes";
const nav = document.getElementById("nav");
for (const name of Object.keys(TABS)) {
  const a = document.createElement("a");
  a.textContent = name; a.id = "tab-" + name;
  a.onclick = () => { current = name; hideDetail(); refresh(); };
  nav.appendChild(a);
}
function esc(s) {
  // cluster-provided strings (actor/task names come from user code) must
  // never reach innerHTML unescaped — stored-XSS guard
  return String(s).replace(/[&<>"']/g, c => ({
    "&": "&amp;", "<": "&lt;", ">": "&gt;",
    '"': "&quot;", "'": "&#39;"})[c]);
}
function cell(v) {
  if (v === null || v === undefined) return "";
  if (typeof v === "object") return esc(JSON.stringify(v));
  return esc(v);
}
function statePill(v) {
  const s = esc(v);
  const cls = /^[A-Za-z_]+$/.test(String(v)) ? String(v) : "";
  return `<span class="pill ${cls}">${s}</span>`;
}
// -- drill-down ------------------------------------------------------------
let lastRows = [];
function showDetail(i) {
  const r = lastRows[i];
  if (!r) return;
  document.getElementById("detail").style.display = "block";
  document.getElementById("dtitle").textContent =
    current + " · " + (r.name || r.actor_id || r.task_id || r.node_id ||
                       r.object_id || r.key || "row " + i);
  document.getElementById("djson").textContent =
    JSON.stringify(r, null, 2);
}
function hideDetail() {
  document.getElementById("detail").style.display = "none";
}
document.getElementById("dclose").onclick = hideDetail;
// -- tooltip ---------------------------------------------------------------
const tip = document.getElementById("tooltip");
function tipShow(ev, html) {
  tip.style.display = "block"; tip.innerHTML = html;
  tip.style.left = (ev.clientX + 12) + "px";
  tip.style.top = (ev.clientY + 12) + "px";
}
function tipHide() { tip.style.display = "none"; }
// -- timeline --------------------------------------------------------------
const SERIES = ["var(--series-1)", "var(--series-2)", "var(--series-3)"];
const nameColor = new Map();  // fixed first-seen assignment, never cycled
function colorFor(name) {
  if (!nameColor.has(name))
    nameColor.set(name, nameColor.size < SERIES.length
                  ? SERIES[nameColor.size] : "var(--series-other)");
  return nameColor.get(name);
}
function renderTimeline(events) {
  const sp = document.getElementById("special");
  const xs = events.filter(e => e.ph === "X" && e.dur > 0);
  if (!xs.length) {
    sp.innerHTML = "<div class='note'>no task events yet — " +
      "run some tasks, then revisit</div>";
    return;
  }
  // axis/lanes derive from the DRAWN window, not all history — else the
  // axis spans undrawn events and recent spans compress into a sliver
  const shown = xs.slice(-2000);
  const t0 = Math.min(...shown.map(e => e.ts));
  const t1 = Math.max(...shown.map(e => e.ts + e.dur));
  const span = Math.max(t1 - t0, 1);
  const lanes = [...new Set(shown.map(e => e.tid))];
  const W = Math.max(600, sp.clientWidth - 10), laneH = 22,
        left = 90, H = lanes.length * laneH + 30;
  let bars = "";
  shown.forEach((e, i) => {
    const y = lanes.indexOf(e.tid) * laneH + 4;
    const x = left + (e.ts - t0) / span * (W - left - 10);
    const w = Math.max(2, e.dur / span * (W - left - 10));
    bars += `<rect data-i="${i}" x="${x.toFixed(1)}" y="${y}"
      width="${w.toFixed(1)}" height="${laneH - 8}" rx="3"
      fill="${colorFor(e.name)}"
      stroke="var(--surface-1)" stroke-width="1"></rect>`;
  });
  const labels = lanes.map((t, j) =>
    `<text class="lane-label" x="4" y="${j * laneH + 15}">` +
    `worker ${esc(t)}</text>`).join("");
  // time axis: start / mid / end ticks in seconds-since-start
  const ticks = [0, 0.5, 1].map(f => {
    const x = left + f * (W - left - 10);
    return `<line x1="${x}" y1="0" x2="${x}" y2="${H - 24}"
              stroke="var(--border)"></line>
            <text x="${x + 3}" y="${H - 10}">` +
           `${(span * f / 1e6).toFixed(2)}s</text>`;
  }).join("");
  sp.innerHTML =
    `<div class="note">task execution spans per worker ` +
    `(last ${Math.min(xs.length, 2000)} of ${xs.length}; color = task ` +
    `name, first three names get hues, the rest gray)</div>` +
    `<svg id="tl" width="${W}" height="${H}"
       style="background:var(--surface-2);border:1px solid var(--border);
              border-radius:6px">${ticks}${labels}${bars}</svg>` +
    `<div class="note" id="tl-legend"></div>`;
  const legend = [...nameColor.entries()].slice(0, 6).map(([n, c]) =>
    `<span style="display:inline-flex;align-items:center;gap:4px;` +
    `margin-right:12px"><span style="width:10px;height:10px;` +
    `border-radius:2px;background:${c};display:inline-block"></span>` +
    `${esc(n)}</span>`).join("");
  document.getElementById("tl-legend").innerHTML = legend;
  document.getElementById("tl").addEventListener("mousemove", ev => {
    const r = ev.target.closest("rect");
    if (!r) { tipHide(); return; }
    const e = shown[+r.dataset.i];
    tipShow(ev, `<b>${esc(e.name)}</b><br>worker ${esc(e.tid)}<br>` +
                `${(e.dur / 1e3).toFixed(2)} ms`);
  });
  document.getElementById("tl").addEventListener("mouseleave", tipHide);
}
// -- metrics ---------------------------------------------------------------
const HISTORY = new Map();  // metric -> [{t, v}], ring of 120
function parseProm(text) {
  const out = [];
  for (const line of text.split("\\n")) {
    if (!line || line.startsWith("#")) continue;
    const m = line.match(/^([a-zA-Z_:][\\w:]*)(\\{[^}]*\\})?\\s+(\\S+)/);
    if (m) out.push({name: m[1] + (m[2] || ""), value: parseFloat(m[3])});
  }
  return out;
}
function spark(hist, color) {
  const W = 216, H = 40;
  if (hist.length < 2)
    return `<svg width="${W}" height="${H}"></svg>`;
  const vs = hist.map(p => p.v);
  const lo = Math.min(...vs), hi = Math.max(...vs), r = (hi - lo) || 1;
  const pts = hist.map((p, i) =>
    `${(i / (hist.length - 1) * (W - 4) + 2).toFixed(1)},` +
    `${(H - 4 - (p.v - lo) / r * (H - 8) + 2).toFixed(1)}`).join(" ");
  return `<svg width="${W}" height="${H}"><polyline points="${pts}"
    fill="none" stroke="${color}" stroke-width="2"
    stroke-linejoin="round"></polyline></svg>`;
}
async function renderMetrics() {
  const sp = document.getElementById("special");
  const text = await (await fetch("/metrics")).text();
  const samples = parseProm(text);
  const now = Date.now();
  for (const s of samples) {
    if (!HISTORY.has(s.name)) HISTORY.set(s.name, []);
    const h = HISTORY.get(s.name);
    h.push({t: now, v: s.value});
    if (h.length > 120) h.shift();
  }
  // cards: core-runtime signal first (queue depth, in-flight, store
  // usage, GCS heartbeat lag), then everything else alphabetically —
  // stable order, so a card never jumps between polls
  const CORE = ["rtpu_scheduler_ready_queue_depth",
    "rtpu_scheduler_inflight_tasks", "rtpu_object_store_bytes_used",
    "rtpu_worker_pool_size", "rtpu_pipe_recv_bytes_total",
    "rtpu_tasks_finished_total", "rtpu_gcs_nodes_alive",
    "rtpu_refcount_entries"];
  const coreRank = n => {
    const i = CORE.findIndex(c => n === c || n.startsWith(c + "{"));
    return i === -1 ? CORE.length : i;
  };
  const ranked = [...HISTORY.entries()]
    .filter(([, h]) => h.length >= 1)
    .sort((a, b) => (coreRank(a[0]) - coreRank(b[0]))
                    || a[0].localeCompare(b[0]));
  const cards = ranked.slice(0, 12).map(([name, h]) => {
    const v = h[h.length - 1].v;
    return `<div class="mcard"><div class="name">${esc(name)}</div>` +
      `<div class="val">${Number.isInteger(v) ? v : v.toFixed(3)}</div>` +
      spark(h, "var(--series-1)") + `</div>`;
  }).join("");
  sp.innerHTML =
    `<div class="mcards">${cards || "<div class='note'>no samples " +
     "yet</div>"}</div>` +
    `<div class="note">history = this page's polls (3s cadence); full ` +
    `sample table below</div>` +
    `<table><thead><tr><th>metric</th><th>value</th></tr></thead>` +
    `<tbody>` + samples.map(s =>
      `<tr><td>${esc(s.name)}</td><td>${s.value}</td></tr>`).join("") +
    `</tbody></table>`;
}
// -- contention ------------------------------------------------------------
async function renderContention() {
  const sp = document.getElementById("special");
  const data = (await (await fetch("/api/contention")).json()).result;
  if (!data || !data.enabled) {
    sp.innerHTML = "<div class='note'>contention profiler disabled " +
      "(RTPU_CONTENTION_PROFILER=0)</div>";
    return;
  }
  const rows = Object.entries(data.locks || {});
  if (!rows.length) {
    sp.innerHTML = "<div class='note'>no instrumented locks touched " +
      "yet</div>";
    return;
  }
  sp.innerHTML =
    `<div class="note">driver-process hot locks, worst cumulative wait ` +
    `first (peer processes' rtpu_lock_* series are on /metrics)</div>` +
    `<table><thead><tr><th>lock</th><th>acquisitions</th>` +
    `<th>contended</th><th>contended %</th><th>total wait (s)</th>` +
    `<th>max wait (s)</th></tr></thead><tbody>` +
    rows.map(([n, s]) =>
      `<tr><td>${esc(n)}</td><td>${s.acquisitions}</td>` +
      `<td>${s.contended}</td><td>${s.contended_pct}</td>` +
      `<td>${s.wait_total_s}</td><td>${s.wait_max_s}</td></tr>`
    ).join("") + `</tbody></table>`;
}
// -- main loop -------------------------------------------------------------
async function refresh() {
  for (const n of Object.keys(TABS))
    document.getElementById("tab-" + n)
      .classList.toggle("active", n === current);
  const tbl = document.getElementById("tbl"),
        sp = document.getElementById("special");
  try {
    if (current === "timeline") {
      tbl.style.display = "none";
      const resp = await fetch("/api/timeline");
      renderTimeline((await resp.json()).result || []);
    } else if (current === "metrics") {
      tbl.style.display = "none";
      await renderMetrics();
    } else if (current === "contention") {
      tbl.style.display = "none";
      await renderContention();
    } else {
      sp.innerHTML = ""; tbl.style.display = "table";
      const resp = await fetch(TABS[current]);
      const data = (await resp.json()).result;
      let rows = Array.isArray(data) ? data
        : (data && data.applications
           ? Object.entries(data.applications).map(
               ([k, v]) => ({name: k, ...v}))
           : Object.entries(data || {}).map(([k, v]) => ({key: k, ...v})));
      lastRows = rows;
      const thead = document.querySelector("#tbl thead");
      const tbody = document.querySelector("#tbl tbody");
      if (!rows.length) { thead.innerHTML = "<tr><th>(empty)</th></tr>";
                          tbody.innerHTML = ""; }
      else {
        const cols = Object.keys(rows[0]);
        thead.innerHTML = "<tr>" + cols.map(c =>
          `<th>${esc(c)}</th>`).join("") + "</tr>";
        tbody.innerHTML = rows.map((r, i) =>
          `<tr data-i="${i}">` + cols.map(c => {
            const v = r[c];
            const isState = ["state", "status", "Alive",
                             "alive"].includes(c);
            return `<td>${isState ? statePill(v) : cell(v)}</td>`;
          }).join("") + "</tr>").join("");
        tbody.onclick = ev => {
          const tr = ev.target.closest("tr");
          if (tr) showDetail(+tr.dataset.i);
        };
      }
    }
    document.getElementById("ts").textContent =
      "updated " + new Date().toLocaleTimeString();
    document.getElementById("err").textContent = "";
  } catch (e) {
    document.getElementById("err").textContent = "fetch failed: " + e;
  }
}
refresh();
setInterval(refresh, 3000);
</script>
</body>
</html>
"""
