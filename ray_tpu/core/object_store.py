"""Shared-memory object store.

Role analog: reference plasma (``src/ray/object_manager/plasma/store.h``) +
``CoreWorkerPlasmaStoreProvider``. Implementation differs deliberately:
instead of a store daemon owning one big dlmalloc arena and serving a
unix-socket protocol, each object is one file in ``/dev/shm`` mmap'd by
writer and readers. Readiness ("sealing") is coordinated by the object
directory in the control plane, so readers never attach before the writer
finished. A C++ arena-backed store can be slotted under the same client API
later (``ray_tpu/_native``).

Small objects (< INLINE_THRESHOLD) never touch the store: they live inline
in the object directory (the reference's in-process memory store analog).
"""

from __future__ import annotations

import mmap
import os
import threading
from typing import Any, Dict, Optional

from ray_tpu import config
from ray_tpu.core import serialization
from ray_tpu.core.ids import ObjectID

INLINE_THRESHOLD = 8192

_SHM_DIR = "/dev/shm"

#: process-local store instrumentation, defined centrally in
#: ``util/metric_defs.py`` (reference ``src/ray/stats/metric_defs.cc``
#: role). Fetched lazily so importing the store never drags the metrics
#: registry into processes that don't serve /metrics; the first
#: StoreClient touches it so a scrape shows the series (at 0) before the
#: first use. metric_defs.get caches + survives clear_registry, so the
#: accessor just rebuilds the dict.

#: pre-sorted tag keys for the hot put path (merging/sorting a one-tag
#: dict per put is pure overhead there)
_PATH_KEYS = {p: (("path", p),) for p in ("inline", "arena", "file",
                                          "spill")}
_NO_TAGS = ()


def _store_metrics():
    from ray_tpu.util import metric_defs as md

    return {
        "put_seconds": md.get("rtpu_object_store_put_seconds"),
        "get_seconds": md.get("rtpu_object_store_get_seconds"),
        "puts": md.get("rtpu_object_store_puts_total"),
        "put_bytes": md.get("rtpu_object_store_put_bytes_total"),
        "spilled_bytes": md.get("rtpu_object_store_spilled_bytes_total"),
        "spilled_objects": md.get(
            "rtpu_object_store_spilled_objects_total"),
        "restored_bytes": md.get(
            "rtpu_object_store_restored_bytes_total"),
        "restored_objects": md.get(
            "rtpu_object_store_restored_objects_total"),
        "spill_read_bytes": md.get(
            "rtpu_object_store_spill_read_bytes_total"),
    }


def _seg_path(session: str, obj_id: ObjectID) -> str:
    return os.path.join(_SHM_DIR, f"rtpu-{session}-{obj_id.hex()}")


def _spill_dir(session: str) -> str:
    return os.path.join("/tmp", f"rtpu-spill-{session}")


def _spill_path(session: str, obj_id: ObjectID) -> str:
    return os.path.join(_spill_dir(session), obj_id.hex())


class _Pinned:
    """A mapped segment kept alive while any deserialized view exists.

    ``fd == -2`` marks a native-arena pin; ``baseline`` is the refcount of
    the view's base exporter right after pinning — a later refcount above
    it means deserialized zero-copy views are still alive.
    """

    __slots__ = ("mm", "fd", "size", "baseline")

    def __init__(self, mm, fd: int, size: int, baseline: int = 0):
        self.mm = mm
        self.fd = fd
        self.size = size
        self.baseline = baseline


class StoreClient:
    """Per-process object-store client.

    Backend selection: the C++ arena store (``native/store.cc`` via
    ``ray_tpu._native``) when the library builds/loads — one shm arena per
    session with a free-list allocator, refcounts, and LRU eviction (the
    plasma-role design) — else the file-per-object fallback above. Both
    share this client API; ``RTPU_NATIVE_STORE=0`` forces the fallback.
    """

    def __init__(self, session: str):
        self.session = session
        self._pins: Dict[ObjectID, _Pinned] = {}
        self._lock = threading.Lock()
        self._arena = None
        if config.get("native_store"):
            try:
                from ray_tpu._native import NativeArena

                capacity = int(config.get("store_capacity"))
                self._arena = NativeArena(session, capacity)
            except Exception as e:
                # Loud fallback: a process silently diverging to the file
                # backend while peers use the arena cannot read their
                # arena-stored objects.
                import logging

                logging.getLogger(__name__).warning(
                    "native object store unavailable (%s); "
                    "falling back to file-per-object segments", e)
                self._arena = None
        self._spill_threshold = int(config.get("spill_threshold"))
        # Running total of THIS client's file-segment bytes: the spill
        # check must be O(1), not a /dev/shm scan per put (store_bytes()
        # stays the accurate cross-process accounting API).
        self._file_bytes = 0
        _store_metrics()  # register the series for /metrics scrapes
        self._register_gauges()

    def _register_gauges(self) -> None:
        """Sampled store gauges, refreshed by the metrics collector hook
        at every exposition/federation snapshot. Weakly bound: a client
        dropped by shutdown unregisters itself on the next run, so
        repeated init/shutdown cycles don't accumulate hooks."""
        import weakref

        from ray_tpu.util import metric_defs, metrics

        used = metric_defs.get("rtpu_object_store_bytes_used")
        cap = metric_defs.get("rtpu_object_store_capacity_bytes")
        pins = metric_defs.get("rtpu_object_store_pins")
        spill_dir = metric_defs.get("rtpu_object_store_spill_dir_bytes")
        capacity = int(config.get("store_capacity"))
        wr = weakref.ref(self)
        # spill_dir_bytes is a directory scan (one stat per spilled
        # object) over a SHARED per-node dir; collectors fire on every
        # snapshot (worker delta push ~2s, heartbeat ~2s, scrapes), so
        # rate-limit the scan AND sample it only outside workers — N
        # workers rescanning the same dir would multiply identical
        # node-wide sweeps (the driver/daemon series carries the value)
        import os as _os

        sample_spill = _os.environ.get("RTPU_WORKER") != "1"
        spill_cache = [0.0, 0.0]  # [last_scan_monotonic, last_value]

        def collect():
            import time as _time

            c = wr()
            if c is None:
                metrics.unregister_collector(collect)
                return
            total = c._file_bytes
            if c._arena is not None:
                try:
                    total += c._arena.stats()["used"]
                except Exception:
                    pass
            used.set(total)
            cap.set(capacity)
            pins.set(len(c._pins))
            if sample_spill:
                now = _time.monotonic()
                if now - spill_cache[0] >= 5.0:
                    spill_cache[0] = now
                    spill_cache[1] = c.spill_dir_bytes()
                spill_dir.set(spill_cache[1])

        self._collector = collect
        metrics.register_collector(collect)

    # -- write path -------------------------------------------------------

    def put(self, obj_id: ObjectID, value: Any):
        """Serialize ``value``; returns ``(inline, size)``.

        ``inline`` is the serialized blob when small enough to live in the
        directory (caller ships it over the control channel), else None
        with the bytes written to a shm segment. ``size`` is the
        serialized size either way — callers report it to the directory so
        peers can plan chunked pulls without re-statting the segment.
        """
        data, buffers = serialization.serialize(value)
        return self.put_parts(obj_id, data, buffers)

    def put_parts(self, obj_id: ObjectID, data: bytes, buffers):
        """Like ``put`` but takes an already-serialized (data, buffers) pair
        so callers that must size-check first don't serialize twice.

        Idempotent on duplicate ids: a lineage re-execution re-writes every
        return of the producing task, and siblings that survived the loss
        keep their existing segment (deterministic tasks produce the same
        bytes)."""
        import time as _time

        from ray_tpu.util import failpoints

        # chaos site: a raised seal failure surfaces as a store write
        # error (the producing task errors; retry_exceptions re-runs it)
        failpoints.hit("store.seal")
        m = _store_metrics()
        size = serialization.serialized_size(data, buffers)
        t0 = _time.perf_counter()
        if size < INLINE_THRESHOLD:
            out = bytearray(size)
            serialization.write_into(memoryview(out), data, buffers)
            self._note_put(m, "inline", size, t0)
            return bytes(out), size
        if self.contains(obj_id):
            return None, size  # already present (lineage re-run survivor)
        if self._arena is not None:
            view = self._arena.create(obj_id.binary(), size)
            if view is not None:
                serialization.write_into(view, data, buffers)
                del view
                self._arena.seal(obj_id.binary())
                # The create-ref is NOT released: it is the object
                # directory's reference, dropped only by delete(). Sealed
                # objects with it held are never evicted, so live
                # ObjectRefs can't lose data to allocation pressure.
                self._note_put(m, "arena", size, t0)
                return None, size
            # arena full: fall through to a file segment (never evict
            # referenced objects to make room)
        # Spilling (reference raylet LocalObjectManager::SpillObjects):
        # once shm usage crosses the threshold, new large objects go to
        # disk instead of RAM-backed /dev/shm; reads are transparent.
        arena_used = self._arena.stats()["used"] if self._arena else 0
        spill = (arena_used + self._file_bytes + size
                 > self._spill_threshold)
        if spill:
            # spill path: STREAM the serialized layout through the codec
            # (native lz4 / zlib) block by block — disk bandwidth is the
            # spill ceiling, so bytes saved are wall time saved on BOTH
            # the spill and the later restore, and peak extra heap stays
            # one block (spills fire exactly when memory is tight)
            from ray_tpu.core import spill_codec

            os.makedirs(_spill_dir(self.session), exist_ok=True)
            path = _spill_path(self.session, obj_id)
            spill_codec.write_spill_stream(
                path, size,
                serialization.iter_serialized_blocks(
                    data, buffers, spill_codec.BLOCK_RAW))
            m["spilled_bytes"].inc(size)  # logical, as always
            m["spilled_objects"].inc()
            self._note_spill_event(obj_id, size, "put")
            self._note_put(m, "spill", size, t0)
            return None, size
        path = _seg_path(self.session, obj_id)
        fd = os.open(path, os.O_CREAT | os.O_RDWR | os.O_EXCL, 0o600)
        try:
            os.ftruncate(fd, size)
            mm = mmap.mmap(fd, size)
            serialization.write_into(memoryview(mm), data, buffers)
        finally:
            os.close(fd)
        mm.close()
        self._file_bytes += size
        self._note_put(m, "file", size, t0)
        return None, size

    @staticmethod
    def _note_spill_event(obj_id: ObjectID, size: int, how: str) -> None:
        """THE object_spill emit site (one call site for the event-name
        catalog): both the put-path overflow spill and the chunked-pull
        writer's spill report through here."""
        try:
            from ray_tpu.util import events

            events.emit("object_spill", object_id=obj_id.hex()[:16],
                        size=size, how=how)
        except Exception:
            pass

    @staticmethod
    def _note_put(m, path: str, size: int, t0: float) -> None:
        import time as _time

        try:
            m["puts"]._inc_key(_PATH_KEYS[path])
            m["put_bytes"]._inc_key(_NO_TAGS, size)
            m["put_seconds"]._observe_key(
                _NO_TAGS, _time.perf_counter() - t0)
        except Exception:
            pass

    def put_serialized(self, obj_id: ObjectID, blob: bytes) -> None:
        """Write an already-serialized blob into a segment (spill-in path)."""
        path = _seg_path(self.session, obj_id)
        fd = os.open(path, os.O_CREAT | os.O_RDWR | os.O_EXCL, 0o600)
        try:
            os.ftruncate(fd, len(blob))
            mm = mmap.mmap(fd, len(blob))
            mm[:] = blob
            mm.close()
        finally:
            os.close(fd)

    # -- read path --------------------------------------------------------

    def get(self, obj_id: ObjectID) -> Any:
        """Deserialize from shm; zero-copy views pin the mapping."""
        import time as _time

        t0 = _time.perf_counter()
        with self._lock:
            pinned = self._pins.get(obj_id)
        if pinned is None and self._arena is not None:
            view = self._arena.get(obj_id.binary())
            if view is not None:
                import numpy as _np

                # Root all exports at a numpy base array: every consumer
                # view chain holds one ref on it, so liveness is
                # observable via getrefcount (the ctypes view itself
                # doesn't expose its export count).
                base = _np.frombuffer(view, dtype=_np.uint8)
                took_pin = False
                with self._lock:
                    existing = self._pins.get(obj_id)
                    if existing is not None:
                        pinned = existing  # lost a pin race
                    else:
                        # Idle refcount as seen from release(): the pin's
                        # ref + getrefcount's argument temp. Anything above
                        # means a consumer export chain is alive.
                        pinned = _Pinned(base, -2, len(view), baseline=2)
                        self._pins[obj_id] = pinned
                        took_pin = True
                if not took_pin:
                    # drop the extra native ref our losing get() took
                    del base, view
                    self._arena.release(obj_id.binary())
        if pinned is None:
            seg = _seg_path(self.session, obj_id)
            spilled = _spill_path(self.session, obj_id)
            if not os.path.exists(seg) and os.path.exists(spilled):
                if self.restore_spilled(obj_id):
                    # restored into the arena or a fresh segment; re-enter
                    # (the spill file is gone, so this recurses only once)
                    return self.get(obj_id)
            # seg -> spill -> seg: a concurrent restorer can unlink the
            # spill file between the exists check and the open, in which
            # case the segment path exists again
            mm = None
            fd_kind = -1
            for path in (seg, spilled, seg):
                if path == spilled:
                    from ray_tpu.core import spill_codec

                    if spill_codec.is_compressed(path):
                        # restore was refused (no shm headroom): inflate
                        # to a HEAP buffer and serve zero-copy views off
                        # it (fd == -3 pin; liveness via the numpy-base
                        # refcount, exactly like the arena pin)
                        blob = spill_codec.read_bytes(path)
                        if blob is None:
                            continue
                        import numpy as _np

                        mm = _np.frombuffer(blob, dtype=_np.uint8)
                        size = len(blob)
                        fd_kind = -3
                        _store_metrics()["spill_read_bytes"].inc(size)
                        break
                try:
                    fd = os.open(path, os.O_RDONLY)
                except FileNotFoundError:
                    continue
                try:
                    size = os.fstat(fd).st_size
                    mm = mmap.mmap(fd, size, prot=mmap.PROT_READ)
                finally:
                    os.close(fd)
                if path == spilled:
                    _store_metrics()["spill_read_bytes"].inc(size)
                break
            if mm is None:
                raise FileNotFoundError(seg)
            with self._lock:
                existing = self._pins.get(obj_id)
                if existing is not None:
                    pinned = existing
                    if fd_kind == -1:
                        mm.close()
                else:
                    pinned = _Pinned(mm, fd_kind, size,
                                     baseline=2 if fd_kind == -3 else 0)
                    self._pins[obj_id] = pinned
        value = serialization.read_from(memoryview(pinned.mm))
        try:
            _store_metrics()["get_seconds"]._observe_key(
                _NO_TAGS, _time.perf_counter() - t0)
        except Exception:
            pass
        return value

    def get_raw(self, obj_id: ObjectID) -> Optional[bytes]:
        """The serialized segment bytes (node-to-node transfer source).

        A copy, not a view: the bytes are shipped over a socket, so pinning
        the mapping would only delay eviction for no benefit.
        """
        if self._arena is not None:
            view = self._arena.get(obj_id.binary())
            if view is not None:
                try:
                    return bytes(view)
                finally:
                    del view
                    self._arena.release(obj_id.binary())
        seg = _seg_path(self.session, obj_id)
        spilled = _spill_path(self.session, obj_id)
        # seg -> spill -> seg: tolerate a concurrent restore unlinking the
        # spill file between candidates
        for path in (seg, spilled, seg):
            if path == spilled:
                from ray_tpu.core import spill_codec

                data = spill_codec.read_bytes(path)  # codec-aware
                if data is None:
                    continue
            else:
                try:
                    with open(path, "rb") as f:
                        data = f.read()
                except FileNotFoundError:
                    continue
            if path == spilled:
                _store_metrics()["spill_read_bytes"].inc(len(data))
            return data
        return None

    def get_raw_chunk(self, obj_id: ObjectID, offset: int,
                      length: int) -> Optional[bytes]:
        """A slice of the serialized segment (chunked node-to-node pull,
        reference ObjectBufferPool chunk-read role): only ``length`` bytes
        are copied, so serving a multi-GB object never materializes it."""
        if self._arena is not None:
            view = self._arena.get(obj_id.binary())
            if view is not None:
                try:
                    return bytes(view[offset:offset + length])
                finally:
                    del view
                    self._arena.release(obj_id.binary())
        seg = _seg_path(self.session, obj_id)
        spilled = _spill_path(self.session, obj_id)
        for path in (seg, spilled, seg):
            if path == spilled:
                from ray_tpu.core import spill_codec

                data = spill_codec.read_range(path, offset, length)
                if data is None:
                    continue
            else:
                try:
                    with open(path, "rb") as f:
                        f.seek(offset)
                        data = f.read(length)
                except FileNotFoundError:
                    continue
            if path == spilled:
                _store_metrics()["spill_read_bytes"].inc(len(data))
            return data
        return None

    def begin_receive(self, obj_id: ObjectID,
                      size: int) -> Optional["IncomingObject"]:
        """Allocate the full segment for an incremental cross-node receive;
        chunks are written at offsets, then sealed. Returns None when the
        object is already present."""
        if self.contains(obj_id):
            return None
        return IncomingObject(self, obj_id, size)

    def contains(self, obj_id: ObjectID) -> bool:
        if obj_id in self._pins:
            return True
        if self._arena is not None and self._arena.contains(obj_id.binary()):
            return True
        return os.path.exists(_seg_path(self.session, obj_id)) or \
            os.path.exists(_spill_path(self.session, obj_id))

    def release(self, obj_id: ObjectID) -> None:
        """Drop this process's pin (views must no longer be used).

        Runs fully under the client lock (pop + liveness check + unpin are
        one critical section): a pop-then-reinsert window would let a
        concurrent ``get`` insert a fresh pin that the reinsert clobbers,
        leaking its native ref.
        """
        import sys

        with self._lock:
            pinned = self._pins.get(obj_id)
            if pinned is None:
                return
            if pinned.fd == -2:
                # Native-pin twin of the mmap path's BufferError guard: if
                # deserialized zero-copy views still reference the arena
                # region (exporter refcount above the pin-time baseline),
                # keep the pin so the bytes can't be freed/reused under
                # them.
                if sys.getrefcount(pinned.mm) > pinned.baseline:
                    return
                del self._pins[obj_id]
                self._arena.release(obj_id.binary())
                return
            if pinned.fd == -3:
                # heap pin (decompressed spill served without restore):
                # same refcount liveness guard; nothing to unmap — the
                # buffer dies with the pin
                if sys.getrefcount(pinned.mm) > pinned.baseline:
                    return
                del self._pins[obj_id]
                return
            try:
                pinned.mm.close()
                del self._pins[obj_id]
            except BufferError:
                # Live views still reference the mapping; keep the pin.
                pass

    def delete(self, obj_id: ObjectID) -> None:
        """Remove the object (owner/driver only)."""
        self.release(obj_id)
        if self._arena is not None:
            self._arena.delete(obj_id.binary())
        seg = _seg_path(self.session, obj_id)
        try:
            self._file_bytes = max(
                0, self._file_bytes - os.stat(seg).st_size)
            os.unlink(seg)
        except FileNotFoundError:
            pass
        try:
            os.unlink(_spill_path(self.session, obj_id))
        except FileNotFoundError:
            pass

    def store_bytes(self) -> int:
        """Total bytes of this session's segments currently in shm."""
        total = 0
        if self._arena is not None:
            total += self._arena.stats()["used"]
        prefix = f"rtpu-{self.session}-"
        try:
            for name in os.listdir(_SHM_DIR):
                if name.startswith(prefix):
                    try:
                        total += os.stat(os.path.join(_SHM_DIR, name)).st_size
                    except OSError:
                        pass
        except OSError:
            pass
        return total

    def report(self) -> dict:
        """Arena occupancy/fragmentation report for `ray_tpu memory` /
        ``state.store_report()``: backend, capacity/used/object counts,
        free-list fragmentation (native arena), file-segment bytes, live
        view pins, and spill-directory bytes."""
        out: dict = {
            "backend": "arena" if self._arena is not None else "file",
            "capacity_bytes": int(config.get("store_capacity")),
            "file_segment_bytes": self._file_bytes,
            "view_pins": len(self._pins),
            "spill_dir_bytes": self.spill_dir_bytes(),
        }
        if self._arena is not None:
            try:
                st = self._arena.stats()
                out["arena_used_bytes"] = st["used"]
                out["arena_objects"] = st["num_objects"]
                frag = self._arena.frag_stats()
                if frag:
                    out.update(frag)
                    cap = st["used"] + frag["free_bytes"]
                    # fragmentation = how much of the free space is NOT
                    # reachable by the single largest allocation
                    out["fragmentation_pct"] = round(
                        100.0 * (1.0 - frag["largest_free_bytes"]
                                 / max(1, frag["free_bytes"])), 1)
                    out["occupancy_pct"] = round(
                        100.0 * st["used"] / max(1, cap), 1)
            except Exception:
                pass
        return out

    def contains_spilled(self, obj_id: ObjectID) -> bool:
        return os.path.exists(_spill_path(self.session, obj_id))

    def spill_dir_bytes(self) -> int:
        """Total bytes currently spilled to disk for this session (node-
        wide: every process of the session writes the same directory)."""
        total = 0
        try:
            with os.scandir(_spill_dir(self.session)) as it:
                for e in it:
                    try:
                        total += e.stat().st_size
                    except OSError:
                        pass
        except OSError:
            pass
        return total

    def restore_spilled(self, obj_id: ObjectID) -> bool:
        """Promote a spilled object back into shared memory (reference
        ``LocalObjectManager`` restore, ``local_object_manager.h:110``):
        later local reads and chunked peer pulls hit shm instead of disk.
        Skipped when restoring would push shm usage back over the spill
        threshold — that pressure is why the object spilled. Concurrency-
        safe across processes: the shm copy lands under arena create/seal
        or an O_EXCL temp file renamed into place, and the spill file is
        unlinked only after the copy is readable."""
        if not config.get("spill_restore"):
            return False
        if self._arena is not None and self._arena.contains(obj_id.binary()):
            return True  # a peer already restored it
        seg = _seg_path(self.session, obj_id)
        if os.path.exists(seg):
            return True
        from ray_tpu.core import spill_codec

        path = _spill_path(self.session, obj_id)
        size = spill_codec.raw_size(path)  # LOGICAL size (codec-aware)
        if size is None:
            return False  # not spilled here
        # headroom gate on the ACCURATE cross-process accounting, not this
        # client's O(1) running total: the process serving a peer pull has
        # written nothing itself, and restoring into a /dev/shm already
        # full of other processes' segments would re-create the very
        # pressure that caused the spill. Restore is rare, so the scan is
        # affordable here (unlike the per-put spill check).
        if self.store_bytes() + size > self._spill_threshold:
            return False  # no shm headroom; serve reads from disk
        restored = False
        if self._arena is not None:
            view = self._arena.create(obj_id.binary(), size)
            if view is not None:
                ok = self._copy_file_into(path, view, size)
                del view
                if not ok:
                    self._arena.delete(obj_id.binary())
                    return False
                self._arena.seal(obj_id.binary())
                # like put_parts: the create-ref IS the directory's
                # reference, dropped only by delete()
                restored = True
        if not restored:
            # arena create returning None can mean FULL or a LOST RACE to
            # a concurrent restorer (duplicate id): re-check before paying
            # for a duplicate file-segment copy of the whole object
            if self._arena is not None and \
                    self._arena.contains(obj_id.binary()):
                return True
            if os.path.exists(seg):
                return True
            part = seg + f".restore-{os.getpid()}"
            try:
                fd = os.open(part, os.O_CREAT | os.O_RDWR | os.O_EXCL, 0o600)
            except OSError:
                return False
            try:
                os.ftruncate(fd, size)
                if size:
                    mm = mmap.mmap(fd, size)
                    ok = self._copy_file_into(path, mm, size)
                    mm.close()
                    if not ok:
                        os.unlink(part)
                        return False
            finally:
                os.close(fd)
            os.rename(part, seg)
            self._file_bytes += size
        try:
            os.unlink(path)
        except OSError:
            pass
        m = _store_metrics()
        m["restored_bytes"].inc(size)
        m["restored_objects"].inc()
        try:
            from ray_tpu.util import events

            events.emit("object_restore", object_id=obj_id.hex()[:16],
                        size=size, into="arena" if restored else "file")
        except Exception:
            pass
        return True

    @staticmethod
    def _copy_file_into(path: str, buf, size: int,
                        chunk: int = 8 << 20) -> bool:
        """Decompress/copy a spill file into a writable buffer in bounded
        chunks — restoring a multi-GB object (the serve path runs this
        inside a chunked peer pull) must never materialize it in this
        heap. ``size`` is the LOGICAL object size (spill_codec.raw_size)."""
        from ray_tpu.core import spill_codec

        return spill_codec.read_into(path, buf, size, chunk=chunk)

    @staticmethod
    def cleanup_session(session: str) -> None:
        try:
            from ray_tpu._native import NativeArena

            NativeArena.destroy(session)
        except Exception:
            pass
        import shutil

        shutil.rmtree(_spill_dir(session), ignore_errors=True)
        prefix = f"rtpu-{session}-"
        try:
            for name in os.listdir(_SHM_DIR):
                if name.startswith(prefix):
                    try:
                        os.unlink(os.path.join(_SHM_DIR, name))
                    except OSError:
                        pass
        except OSError:
            pass


class IncomingObject:
    """Incremental cross-node receive: allocate the full segment up front,
    write chunks at offsets, then seal. Arena-backed when possible
    (create -> seal, so readers never attach early); else a ``.part`` file
    renamed into place on seal — ``contains()`` checks the final path, so a
    partial segment is never visible. Role analog: the reference
    ObjectBufferPool create-and-fill (``object_manager/object_buffer_pool.h``).
    """

    def __init__(self, store: StoreClient, obj_id: ObjectID, size: int):
        self._store = store
        self._oid = obj_id
        self._size = size
        self._view = None
        self._mm = None
        self._path = None
        self._spilled = False
        self._done = False
        if store._arena is not None:
            self._view = store._arena.create(obj_id.binary(), size)
        if self._view is None:
            # same spill decision as put_parts: past the shm threshold,
            # large incoming objects land on disk
            arena_used = (store._arena.stats()["used"]
                          if store._arena else 0)
            self._spilled = (arena_used + store._file_bytes + size
                             > store._spill_threshold)
            if self._spilled:
                os.makedirs(_spill_dir(store.session), exist_ok=True)
                self._path = _spill_path(store.session, obj_id)
            else:
                self._path = _seg_path(store.session, obj_id)
            part = self._path + ".part"
            try:
                fd = os.open(part, os.O_CREAT | os.O_RDWR | os.O_EXCL, 0o600)
            except FileExistsError:
                os.unlink(part)  # stale leftover from an aborted fetch
                fd = os.open(part, os.O_CREAT | os.O_RDWR | os.O_EXCL, 0o600)
            try:
                os.ftruncate(fd, size)
                self._mm = mmap.mmap(fd, size) if size else None
            finally:
                os.close(fd)

    def write(self, offset: int, data: bytes) -> None:
        if self._view is not None:
            self._view[offset:offset + len(data)] = data
        elif self._mm is not None:
            self._mm[offset:offset + len(data)] = data

    def seal(self) -> None:
        self._done = True
        if self._view is not None:
            self._view = None  # release the export before sealing
            self._store._arena.seal(self._oid.binary())
        else:
            if self._mm is not None:
                self._mm.close()
                self._mm = None
            os.rename(self._path + ".part", self._path)
            if self._spilled:
                m = _store_metrics()
                m["spilled_bytes"].inc(self._size)
                m["spilled_objects"].inc()
                ObjectStore._note_spill_event(self._oid, self._size,
                                              "chunked_pull")
            else:
                self._store._file_bytes += self._size

    def abort(self) -> None:
        if self._done:
            return
        self._done = True
        if self._view is not None:
            self._view = None
            self._store._arena.delete(self._oid.binary())
        else:
            if self._mm is not None:
                self._mm.close()
                self._mm = None
            try:
                os.unlink(self._path + ".part")
            except OSError:
                pass
