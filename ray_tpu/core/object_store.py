"""Shared-memory object store.

Role analog: reference plasma (``src/ray/object_manager/plasma/store.h``) +
``CoreWorkerPlasmaStoreProvider``. Implementation differs deliberately:
instead of a store daemon owning one big dlmalloc arena and serving a
unix-socket protocol, each object is one file in ``/dev/shm`` mmap'd by
writer and readers. Readiness ("sealing") is coordinated by the object
directory in the control plane, so readers never attach before the writer
finished. A C++ arena-backed store can be slotted under the same client API
later (``ray_tpu/_native``).

Small objects (< INLINE_THRESHOLD) never touch the store: they live inline
in the object directory (the reference's in-process memory store analog).
"""

from __future__ import annotations

import mmap
import os
import threading
from typing import Any, Dict, Optional

from ray_tpu.core import serialization
from ray_tpu.core.ids import ObjectID

INLINE_THRESHOLD = 8192

_SHM_DIR = "/dev/shm"


def _seg_path(session: str, obj_id: ObjectID) -> str:
    return os.path.join(_SHM_DIR, f"rtpu-{session}-{obj_id.hex()}")


class _Pinned:
    """A mapped segment kept alive while any deserialized view exists."""

    __slots__ = ("mm", "fd", "size")

    def __init__(self, mm: mmap.mmap, fd: int, size: int):
        self.mm = mm
        self.fd = fd
        self.size = size


class StoreClient:
    """Per-process object-store client."""

    def __init__(self, session: str):
        self.session = session
        self._pins: Dict[ObjectID, _Pinned] = {}
        self._lock = threading.Lock()

    # -- write path -------------------------------------------------------

    def put(self, obj_id: ObjectID, value: Any) -> Optional[bytes]:
        """Serialize ``value``.

        Returns the serialized blob if it is small enough to inline in the
        directory (caller ships it over the control channel), else writes a
        shm segment and returns None.
        """
        data, buffers = serialization.serialize(value)
        return self.put_parts(obj_id, data, buffers)

    def put_parts(self, obj_id: ObjectID, data: bytes, buffers) -> Optional[bytes]:
        """Like ``put`` but takes an already-serialized (data, buffers) pair
        so callers that must size-check first don't serialize twice."""
        size = serialization.serialized_size(data, buffers)
        if size < INLINE_THRESHOLD:
            out = bytearray(size)
            serialization.write_into(memoryview(out), data, buffers)
            return bytes(out)
        path = _seg_path(self.session, obj_id)
        fd = os.open(path, os.O_CREAT | os.O_RDWR | os.O_EXCL, 0o600)
        try:
            os.ftruncate(fd, size)
            mm = mmap.mmap(fd, size)
            serialization.write_into(memoryview(mm), data, buffers)
        finally:
            os.close(fd)
        mm.close()
        return None

    def put_serialized(self, obj_id: ObjectID, blob: bytes) -> None:
        """Write an already-serialized blob into a segment (spill-in path)."""
        path = _seg_path(self.session, obj_id)
        fd = os.open(path, os.O_CREAT | os.O_RDWR | os.O_EXCL, 0o600)
        try:
            os.ftruncate(fd, len(blob))
            mm = mmap.mmap(fd, len(blob))
            mm[:] = blob
            mm.close()
        finally:
            os.close(fd)

    # -- read path --------------------------------------------------------

    def get(self, obj_id: ObjectID) -> Any:
        """Deserialize from shm; zero-copy views pin the mapping."""
        with self._lock:
            pinned = self._pins.get(obj_id)
        if pinned is None:
            path = _seg_path(self.session, obj_id)
            fd = os.open(path, os.O_RDONLY)
            try:
                size = os.fstat(fd).st_size
                mm = mmap.mmap(fd, size, prot=mmap.PROT_READ)
            finally:
                os.close(fd)
            pinned = _Pinned(mm, -1, size)
            with self._lock:
                self._pins[obj_id] = pinned
        return serialization.read_from(memoryview(pinned.mm))

    def contains(self, obj_id: ObjectID) -> bool:
        return obj_id in self._pins or os.path.exists(_seg_path(self.session, obj_id))

    def release(self, obj_id: ObjectID) -> None:
        """Drop this process's pin (views must no longer be used)."""
        with self._lock:
            pinned = self._pins.pop(obj_id, None)
        if pinned is not None:
            try:
                pinned.mm.close()
            except BufferError:
                # Live views still reference the mapping; re-pin.
                with self._lock:
                    self._pins[obj_id] = pinned

    def delete(self, obj_id: ObjectID) -> None:
        """Unlink the segment (owner/driver only)."""
        self.release(obj_id)
        try:
            os.unlink(_seg_path(self.session, obj_id))
        except FileNotFoundError:
            pass

    def store_bytes(self) -> int:
        """Total bytes of this session's segments currently in shm."""
        total = 0
        prefix = f"rtpu-{self.session}-"
        try:
            for name in os.listdir(_SHM_DIR):
                if name.startswith(prefix):
                    try:
                        total += os.stat(os.path.join(_SHM_DIR, name)).st_size
                    except OSError:
                        pass
        except OSError:
            pass
        return total

    @staticmethod
    def cleanup_session(session: str) -> None:
        prefix = f"rtpu-{session}-"
        try:
            for name in os.listdir(_SHM_DIR):
                if name.startswith(prefix):
                    try:
                        os.unlink(os.path.join(_SHM_DIR, name))
                    except OSError:
                        pass
        except OSError:
            pass
