"""Exception types surfaced by the runtime.

Role analog: reference ``python/ray/exceptions.py``.
"""

from __future__ import annotations

import traceback


class RayTpuError(Exception):
    pass


class TaskError(RayTpuError):
    """A task raised; re-raised at ``get`` with the remote traceback.

    Carries a machine-readable ``error_type`` (taken from the cause's
    own ``error_type`` attribute when it declares one — e.g. admission
    ``RequestShedError("shed")`` / ``DeadlineExceededError("deadline")``
    — else the cause's class name) so callers classify failures without
    parsing ``str()``; the custom ``__reduce__`` ships the cause and the
    classification across process boundaries, with a representation
    fallback for unpicklable causes (the default Exception reduce would
    silently collapse ``cause`` to its message string)."""

    def __init__(self, cause: BaseException, remote_tb: str = "",
                 task_desc: str = "", error_type: str = None):
        self.cause = cause
        self.remote_tb = remote_tb
        self.task_desc = task_desc
        self.error_type = (error_type if error_type is not None
                           else getattr(cause, "error_type", None)
                           or type(cause).__name__)
        super().__init__(str(cause))

    def __str__(self):
        return (
            f"{type(self.cause).__name__}: {self.cause}\n"
            f"--- remote traceback ({self.task_desc}) ---\n{self.remote_tb}"
        )

    def __reduce__(self):
        try:
            import cloudpickle

            blob = cloudpickle.dumps(self.cause)
        except Exception:
            blob = None
        return (_rebuild_task_error,
                (blob, type(self.cause).__name__, str(self.cause),
                 self.remote_tb, self.task_desc, self.error_type))


def _rebuild_task_error(blob, cause_type: str, cause_str: str,
                        remote_tb: str, task_desc: str,
                        error_type) -> TaskError:
    cause = None
    if blob is not None:
        try:
            import pickle

            cause = pickle.loads(blob)
        except Exception:
            cause = None
    if cause is None:  # unpicklable either way: keep the repr + type
        cause = RuntimeError(f"{cause_type}: {cause_str}")
    return TaskError(cause, remote_tb, task_desc, error_type)


def wrap_current_exception(task_desc: str = "") -> TaskError:
    import sys

    et, ev, tb = sys.exc_info()
    return TaskError(ev, "".join(traceback.format_exception(et, ev, tb)), task_desc)


class WorkerCrashedError(RayTpuError):
    pass


class ActorDiedError(RayTpuError):
    pass


class ActorUnavailableError(RayTpuError):
    pass


class GetTimeoutError(RayTpuError, TimeoutError):
    pass


class ObjectLostError(RayTpuError):
    pass


class RuntimeEnvSetupError(RayTpuError):
    pass


class TaskCancelledError(RayTpuError):
    pass


class PlacementGroupUnavailableError(RayTpuError):
    pass
