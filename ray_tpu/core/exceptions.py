"""Exception types surfaced by the runtime.

Role analog: reference ``python/ray/exceptions.py``.
"""

from __future__ import annotations

import traceback


class RayTpuError(Exception):
    pass


class TaskError(RayTpuError):
    """A task raised; re-raised at ``get`` with the remote traceback."""

    def __init__(self, cause: BaseException, remote_tb: str = "", task_desc: str = ""):
        self.cause = cause
        self.remote_tb = remote_tb
        self.task_desc = task_desc
        super().__init__(str(cause))

    def __str__(self):
        return (
            f"{type(self.cause).__name__}: {self.cause}\n"
            f"--- remote traceback ({self.task_desc}) ---\n{self.remote_tb}"
        )


def wrap_current_exception(task_desc: str = "") -> TaskError:
    import sys

    et, ev, tb = sys.exc_info()
    return TaskError(ev, "".join(traceback.format_exception(et, ev, tb)), task_desc)


class WorkerCrashedError(RayTpuError):
    pass


class ActorDiedError(RayTpuError):
    pass


class ActorUnavailableError(RayTpuError):
    pass


class GetTimeoutError(RayTpuError, TimeoutError):
    pass


class ObjectLostError(RayTpuError):
    pass


class RuntimeEnvSetupError(RayTpuError):
    pass


class TaskCancelledError(RayTpuError):
    pass


class PlacementGroupUnavailableError(RayTpuError):
    pass
