"""GC-safe reference bookkeeping helpers shared by driver and worker.

``ObjectRef.__del__`` can fire at ANY allocation point via cycle
collection — including on a thread that already holds the process's ref
lock or a transport send lock — so the __del__ hook must take no locks and
do no IO. Both runtimes follow the same shape (advisor r3):

- the hook only appends the dropped oid to a plain deque
  (``deque.append`` is atomic, lock-free);
- normal code paths call :meth:`DeferredDrops.drain`, which applies the
  queued drops under the owner's lock and then flushes casts;
- 0<->1 pin transitions are recorded IN ORDER under the owner's lock into
  an :class:`OrderedCastFlusher`, and shipped outside it (network/pipe IO
  under the ref lock widened the deadlock window).

Role analog: reference ``ReferenceCounter`` (``reference_count.h:61``)
does this with re-entrancy-safe C++ locks; Python finalizers need the
queue-and-drain shape instead.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Callable


class OrderedCastFlusher:
    """Ship queued items with a single active flusher, preserving order.

    ``append`` must be called under the owner's ref lock so the queue order
    matches transition order. ``flush`` is called OUTSIDE that lock: the
    try-lock makes one thread the flusher; a loser's freshly-appended items
    are picked up by the winner's outer re-check loop (after the winner
    releases, it re-checks the queue; a loser that failed the try-lock
    appended strictly before the winner's release), so nothing strands.
    """

    def __init__(self, send: Callable, batch: bool = False):
        self._q: deque = deque()
        self._flush_lock = threading.Lock()
        # batch=False: ``send`` is called once per item.
        # batch=True:  ``send`` receives the LIST of items drained in one
        # pass — the worker ships refpin transitions as a single
        # ``refpins`` cast instead of one pipe message per transition
        # (r13 control-message coalescing; order inside the list is the
        # transition order).
        self._send = send  # exceptions swallowed
        self._batch = batch

    def append(self, item) -> None:
        self._q.append(item)

    def clear(self) -> None:
        self._q.clear()

    def flush(self) -> None:
        while self._q:
            if not self._flush_lock.acquire(blocking=False):
                return
            try:
                if self._batch:
                    items = []
                    while True:
                        try:
                            items.append(self._q.popleft())
                        except IndexError:
                            break
                    if items:
                        try:
                            self._send(items)
                        except Exception:
                            pass
                    continue
                while True:
                    try:
                        item = self._q.popleft()
                    except IndexError:
                        break
                    try:
                        self._send(item)
                    except Exception:
                        pass
            finally:
                self._flush_lock.release()


class DeferredDrops:
    """Drain-queue for ref drops queued by ``ObjectRef.__del__``.

    ``append`` (the __del__ hook) is the bare deque append. ``drain``
    applies each queued oid via ``apply_locked`` under ``lock``, then calls
    ``after`` (typically the cast flusher) outside it.
    """

    def __init__(self, lock: threading.Lock, apply_locked: Callable,
                 after: Callable):
        self._q: deque = deque()
        self._lock = lock
        self._apply_locked = apply_locked
        self._after = after

    @property
    def append(self) -> Callable:
        return self._q.append

    def drain(self) -> None:
        while self._q:
            with self._lock:
                while True:
                    try:
                        b = self._q.popleft()
                    except IndexError:
                        break
                    self._apply_locked(b)
            self._after()
