"""RemoteFunction — the object created by ``@ray_tpu.remote`` on a function.

Role analog: reference ``python/ray/remote_function.py`` (``RemoteFunction.
_remote :266`` → submit). The function body is cloudpickled once and cached
in the GCS function table keyed by digest; specs carry only the digest.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from ray_tpu.core import task_spec as ts


def _normalize_resources(opts: Dict[str, Any], default_cpu: float = 1.0) -> Dict[str, float]:
    res: Dict[str, float] = {}
    num_cpus = opts.get("num_cpus")
    res["CPU"] = float(default_cpu if num_cpus is None else num_cpus)
    if opts.get("num_tpus"):
        res["TPU"] = float(opts["num_tpus"])
    if opts.get("num_gpus"):
        res["GPU"] = float(opts["num_gpus"])
    for k, v in (opts.get("resources") or {}).items():
        res[k] = float(v)
    res = {k: v for k, v in res.items() if v}
    return res


def _pg_options(opts: Dict[str, Any]):
    pg = opts.get("placement_group")
    strategy = opts.get("scheduling_strategy")
    bundle_index = opts.get("placement_group_bundle_index", -1)
    if strategy is not None and hasattr(strategy, "placement_group"):
        pg = strategy.placement_group
        bundle_index = getattr(strategy, "placement_group_bundle_index", -1)
        if bundle_index is None:
            bundle_index = -1
    if pg is not None and not isinstance(pg, (bytes, bytearray)):
        pg = pg.id.binary()
    return pg, bundle_index


def _strategy_spec(opts: Dict[str, Any]):
    """Encode a scheduling strategy into the task spec (cluster placement
    honors it; single-node ignores it). Reference strategies:
    "SPREAD"/"DEFAULT" strings and NodeAffinitySchedulingStrategy."""
    strategy = opts.get("scheduling_strategy")
    if strategy is None or hasattr(strategy, "placement_group"):
        return None
    if isinstance(strategy, str):
        up = strategy.upper()
        if up == "SPREAD":
            return ("spread",)
        if up == "RANDOM":
            # reference random_scheduling_policy.h: uniform over feasible
            # nodes (useful for load smoke-spreading without the hybrid
            # policy's utilization scoring)
            return ("random",)
        return None
    if hasattr(strategy, "node_id"):
        node_id = strategy.node_id
        if isinstance(node_id, str):
            node_id = bytes.fromhex(node_id)
        return ("node_affinity", node_id, bool(getattr(strategy, "soft",
                                                       False)))
    if hasattr(strategy, "hard") and hasattr(strategy, "soft"):
        def enc(preds):
            return tuple((str(k), getattr(op, "op", "in"),
                          tuple(getattr(op, "values", ())))
                         for k, op in preds.items())

        return ("node_labels", enc(strategy.hard), enc(strategy.soft))
    return None


class RemoteFunction:
    def __init__(self, fn, options: Dict[str, Any]):
        self._function = fn
        self._options = dict(options or {})
        self._fn_blob = ts.pickle_fn(fn)
        self._fn_hash = ts.fn_digest(self._fn_blob)
        # submit fast-path (r13): the spec template + function-table
        # registration are cached per (function, option-set) — this
        # instance IS that key (``options()`` returns a fresh instance,
        # so a changed option set can never reuse a stale template)
        self._tmpl = None
        self._tmpl_rt = None
        self.__name__ = getattr(fn, "__name__", "remote_fn")
        self.__doc__ = getattr(fn, "__doc__", None)

    def __call__(self, *a, **kw):
        raise TypeError(
            f"remote function {self.__name__} cannot be called directly; "
            f"use {self.__name__}.remote()"
        )

    def bind(self, *args, **kwargs):
        """Build a lazy DAG node for this function (reference ``fn.bind``)."""
        from ray_tpu.dag.dag_node import FunctionNode

        return FunctionNode(self, args, kwargs)

    def options(self, **new_options):
        merged = {**self._options, **new_options}
        rf = RemoteFunction.__new__(RemoteFunction)
        rf._function = self._function
        rf._options = merged
        rf._fn_blob = self._fn_blob
        rf._fn_hash = self._fn_hash
        rf._tmpl = None       # fresh option set -> fresh template
        rf._tmpl_rt = None
        rf.__name__ = self.__name__
        rf.__doc__ = self.__doc__
        return rf

    def _template(self, rt) -> Dict[str, Any]:
        """The cached invariant spec parts for this (function, option-set)
        against ``rt`` — resources/pg/strategy/retry normalization and
        runtime_env packaging run ONCE, not per submission. Keyed on the
        runtime identity so an init/shutdown cycle (or a worker-side
        clone) rebuilds and re-registers."""
        if self._tmpl is not None and self._tmpl_rt is rt:
            return self._tmpl
        rt.ensure_fn(self._fn_hash, self._fn_blob)
        pg, bundle_index = _pg_options(self._options)
        renv = self._options.get("runtime_env")
        if renv:
            # no-ops without py_modules; raises loudly on pip/conda/etc
            from ray_tpu.runtime_env import package_runtime_env

            renv = package_runtime_env(renv, rt)
            self._options = {**self._options, "runtime_env": renv}
        num_returns = self._options.get("num_returns", 1)
        streaming = num_returns in ("streaming", "dynamic")
        # retry_exceptions shares the max_retries budget (reference
        # semantics) — opting in without an explicit max_retries gets the
        # reference default of 3 instead of the fail-fast 0, so
        # @remote(retry_exceptions=True) is never silently inert
        max_retries = self._options.get("max_retries")
        if max_retries is None:
            max_retries = 3 if self._options.get("retry_exceptions") else 0
        bp = self._options.get("_generator_backpressure_num_objects")
        self._tmpl = ts.make_task_template(
            self._fn_hash,
            num_returns=1 if streaming else int(num_returns),
            resources=_normalize_resources(self._options),
            name=self._options.get("name", self.__name__),
            max_retries=int(max_retries),
            placement_group_id=pg,
            bundle_index=bundle_index,
            runtime_env=self._options.get("runtime_env"),
            # True = retry any application error; a list/tuple of exception
            # types retries only those (reference retry_exceptions forms)
            retry_exceptions=self._options.get("retry_exceptions", False),
            streaming=streaming,
            # producer pauses when this many yields are unconsumed
            # (reference generator_waiter.cc)
            stream_backpressure=int(bp) if streaming and bp else 0,
            strategy=_strategy_spec(self._options),
        )
        self._tmpl_rt = rt
        return self._tmpl

    def remote(self, *args, **kwargs):
        from ray_tpu.core.runtime import _get_runtime

        rt = _get_runtime()
        tmpl = self._template(rt)
        enc_args, enc_kwargs, nested_refs = ts.encode_args(args, kwargs, rt)
        spec = ts.spec_from_template(tmpl, enc_args, enc_kwargs)
        if nested_refs:
            spec["borrowed"] = nested_refs
        if spec.get("streaming"):
            # the declared return becomes the end sentinel; yields surface
            # as they are produced (reference ObjectRefGenerator,
            # _raylet.pyx:273)
            from ray_tpu.core.object_ref import ObjectRefGenerator

            refs = rt.submit(spec)
            return ObjectRefGenerator(
                spec["task_id"], refs[0],
                backpressured=bool(spec.get("stream_backpressure")),
                owner=getattr(rt, "cluster_node_id", None))
        refs = rt.submit(spec)
        if self._options.get("num_returns", 1) == 1:
            return refs[0]
        return refs

    def __reduce__(self):
        return (_rebuild_remote_function, (self._fn_blob, self._options))


def _rebuild_remote_function(fn_blob: bytes, options: Dict[str, Any]) -> RemoteFunction:
    import cloudpickle

    rf = RemoteFunction.__new__(RemoteFunction)
    rf._function = cloudpickle.loads(fn_blob)
    rf._options = options
    rf._fn_blob = fn_blob
    rf._fn_hash = ts.fn_digest(fn_blob)
    rf._tmpl = None
    rf._tmpl_rt = None
    rf.__name__ = getattr(rf._function, "__name__", "remote_fn")
    rf.__doc__ = getattr(rf._function, "__doc__", None)
    return rf
