"""Compressed spill-file IO (the disk leg of the object store).

Role analog: the reference's spilled-object URI layer with IO workers
(``local_object_manager.h``) — here the win is bandwidth: spill files live
on slow disk, so trading CPU for bytes moves the spill/restore ceiling.
The codec is the native LZ4 block implementation (``native/pipe.cc``; no
lz4/zstd python modules exist in the image), with zlib as the pure-Python
fallback and ``RTPU_SPILL_COMPRESSION=off`` as the kill switch.

File format (self-describing; readers handle every codec + legacy raw)::

    magic  b"RTPZ1"
    u8     codec        (1 = lz4-native, 2 = zlib)
    u64le  raw_size     (logical serialized object size)
    u32le  block_raw    (raw bytes per block, last may be short)
    blocks: [ u32le comp_len  u32le raw_len  payload ]*

A block whose ``comp_len == raw_len`` is stored RAW (incompressible
guard); whole-file incompressibility falls back to a headerless raw file,
indistinguishable from the legacy format. Block framing exists so
``read_range`` (chunked peer pulls) can seek without inflating the whole
object, and bounds decompress buffers on restore.

Legacy/raw detection is unambiguous: spill files always hold
serialization-format payloads whose first byte is 0x00 (little-endian
``serialization.MAGIC``), which can never match ``RTPZ1``.
"""

from __future__ import annotations

import os
import struct
from typing import Optional

from ray_tpu import config

MAGIC = b"RTPZ1"
CODEC_LZ4 = 1
CODEC_ZLIB = 2
#: raw bytes per compressed block (seekable unit for read_range)
BLOCK_RAW = 4 << 20

_HDR = struct.Struct("<5sBQI")      # magic, codec, raw_size, block_raw
_BLK = struct.Struct("<II")         # comp_len, raw_len


def _codec_metrics():
    from ray_tpu.util import metric_defs as md

    return {
        "comp_bytes": md.get(
            "rtpu_object_store_spill_compressed_bytes_total"),
        "ratio": md.get("rtpu_object_store_spill_compression_ratio"),
    }


def _pick_codec() -> int:
    """Resolve the configured codec to a concrete one, or 0 for off."""
    mode = str(config.get("spill_compression")).lower()
    if mode in ("off", "0", "false", "no", "none", ""):
        return 0
    if mode == "zlib":
        return CODEC_ZLIB
    # auto / lz4: native when the .so carries the codec, else zlib
    try:
        from ray_tpu import _native

        if _native.load_store_lib() is not None and \
                _native.native_status()["lz4"]:
            return CODEC_LZ4
    except Exception:
        pass
    return 0 if mode == "lz4" else CODEC_ZLIB


def _compress_block(codec: int, block) -> Optional[bytes]:
    if codec == CODEC_LZ4:
        from ray_tpu import _native

        return _native.lz4_compress(block)
    import zlib

    return zlib.compress(bytes(block), 1)


def _decompress_block(codec: int, payload: bytes, raw_len: int) -> bytes:
    if codec == CODEC_LZ4:
        from ray_tpu import _native

        return _native.lz4_decompress(payload, raw_len)
    import zlib

    out = zlib.decompress(payload)
    if len(out) != raw_len:
        raise ValueError("corrupt zlib spill block")
    return out


def write_spill_stream(path: str, size: int, blocks) -> int:
    """STREAMING spill write: ``blocks`` yields the serialized object in
    ``BLOCK_RAW``-sized chunks (last short) — see
    ``serialization.iter_serialized_blocks``. Each block is compressed
    and written as it arrives, so a multi-GB spill's peak extra heap is
    one block (incompressible blocks are framed raw, bounding the
    worst-case file at size + ~8 bytes per block). O_EXCL like the
    legacy writer (concurrent spillers of one object: first wins).
    Returns the PHYSICAL byte count written."""
    codec = _pick_codec()
    cap = int(config.get("spill_compress_max_bytes"))
    if cap and size > cap:
        # huge objects stay RAW: a compressed spill served without shm
        # headroom must inflate to anonymous heap, while a raw file is
        # mmap-servable (page-cache backed, reclaimable) — the cap keeps
        # that worst case bounded on exactly the memory-tight boxes that
        # spill in the first place
        codec = 0
    fd = os.open(path, os.O_CREAT | os.O_WRONLY | os.O_EXCL, 0o600)
    physical = 0
    try:
        if not codec or size == 0:
            for block in blocks:  # raw legacy-format file
                os.write(fd, block)
                physical += len(block)
            return physical
        hdr = _HDR.pack(MAGIC, codec, size, BLOCK_RAW)
        os.write(fd, hdr)
        physical = len(hdr)
        for block in blocks:
            comp = _compress_block(codec, block)
            if comp is None or len(comp) >= len(block):
                comp = bytes(block)  # incompressible block stays raw
            os.write(fd, _BLK.pack(len(comp), len(block)))
            os.write(fd, comp)
            physical += _BLK.size + len(comp)
    finally:
        os.close(fd)
    if physical < size:
        try:
            m = _codec_metrics()
            m["comp_bytes"].inc(physical)
            m["ratio"].observe(size / max(1, physical))
        except Exception:
            pass
    return physical


def write_spill(path: str, buf) -> int:
    """Whole-buffer convenience wrapper over ``write_spill_stream``."""
    mv = memoryview(buf).cast("B")
    size = len(mv)
    return write_spill_stream(
        path, size,
        (bytes(mv[off:off + BLOCK_RAW])
         for off in range(0, size, BLOCK_RAW)))


def _read_header(f) -> Optional[tuple]:
    head = f.read(_HDR.size)
    if len(head) < _HDR.size or not head.startswith(MAGIC):
        return None
    magic, codec, raw_size, block_raw = _HDR.unpack(head)
    return codec, raw_size, block_raw


def raw_size(path: str) -> Optional[int]:
    """Logical (decompressed) size of a spill file; None if absent."""
    try:
        with open(path, "rb") as f:
            hdr = _read_header(f)
            if hdr is None:
                return os.fstat(f.fileno()).st_size
            return hdr[1]
    except OSError:
        return None


def is_compressed(path: str) -> bool:
    try:
        with open(path, "rb") as f:
            return f.read(len(MAGIC)) == MAGIC
    except OSError:
        return False


def read_into(path: str, buf, size: int, chunk: int = 8 << 20) -> bool:
    """Decompress (or plain-copy) the spill file into a writable buffer
    of exactly ``size`` bytes — the restore path. Bounded memory: one
    block (compressed) at a time."""
    try:
        with open(path, "rb") as f:
            hdr = _read_header(f)
            if hdr is None:
                f.seek(0)
                off = 0
                while off < size:
                    data = f.read(min(chunk, size - off))
                    if not data:
                        return False  # truncated under us
                    buf[off:off + len(data)] = data
                    off += len(data)
                return off == size
            codec, raw_total, _block_raw = hdr
            if raw_total != size:
                return False
            mv = memoryview(buf)
            off = 0
            while off < size:
                bh = f.read(_BLK.size)
                if len(bh) < _BLK.size:
                    return False
                comp_len, raw_len = _BLK.unpack(bh)
                payload = f.read(comp_len)
                if len(payload) < comp_len:
                    return False
                if comp_len == raw_len:
                    mv[off:off + raw_len] = payload
                elif codec == CODEC_LZ4:
                    # inflate DIRECTLY into the destination (arena view /
                    # mmap) — no per-block heap copy on the restore path
                    from ray_tpu import _native

                    if _native.lz4_decompress_into(
                            payload, mv[off:off + raw_len]) != raw_len:
                        return False
                else:
                    mv[off:off + raw_len] = _decompress_block(
                        codec, payload, raw_len)
                off += raw_len
            return off == size
    except (OSError, ValueError, RuntimeError):
        return False


def read_bytes(path: str) -> Optional[bytes]:
    """The whole logical payload (get_raw on a spilled object)."""
    size = raw_size(path)
    if size is None:
        return None
    out = bytearray(size)
    if not read_into(path, out, size):
        return None
    return bytes(out)


#: path -> (stat signature, [file offset of block i's header]) — spill
#: files are immutable once written (O_EXCL create, unlink-only), so a
#: per-process index makes chunked peer pulls O(range) instead of
#: re-walking every 8-byte block header from the file head per chunk.
#: Bounded FIFO; entries for vanished/replaced files drop on sig mismatch.
_range_index: dict = {}
_RANGE_INDEX_MAX = 32


def _block_index(path: str, f) -> Optional[list]:
    try:
        st = os.fstat(f.fileno())
        sig = (st.st_ino, st.st_size, st.st_mtime_ns)
    except OSError:
        return None
    ent = _range_index.get(path)
    if ent is not None and ent[0] == sig:
        return ent[1]
    offsets = []
    pos = _HDR.size
    end = st.st_size
    while pos < end:
        offsets.append(pos)
        f.seek(pos)
        bh = f.read(_BLK.size)
        if len(bh) < _BLK.size:
            return None
        comp_len, _raw_len = _BLK.unpack(bh)
        pos += _BLK.size + comp_len
    while len(_range_index) >= _RANGE_INDEX_MAX:
        try:  # concurrent evictors may race on the same first key
            _range_index.pop(next(iter(_range_index)), None)
        except (StopIteration, RuntimeError):
            break
    _range_index[path] = (sig, offsets)
    return offsets


def read_range(path: str, offset: int, length: int) -> Optional[bytes]:
    """A logical slice (chunked peer pull of a spilled object): jumps
    straight to the blocks overlapping the range via the per-file block
    index (every block holds exactly ``block_raw`` logical bytes except
    the last), inflating only those."""
    try:
        with open(path, "rb") as f:
            hdr = _read_header(f)
            if hdr is None:
                f.seek(offset)
                return f.read(length)
            codec, raw_total, block_raw = hdr
            end = min(offset + length, raw_total)
            if offset >= raw_total:
                return b""
            index = _block_index(path, f)
            if index is None:
                return None
            out = bytearray()
            for bi in range(offset // block_raw,
                            (end + block_raw - 1) // block_raw):
                if bi >= len(index):
                    return None
                f.seek(index[bi])
                bh = f.read(_BLK.size)
                if len(bh) < _BLK.size:
                    return None
                comp_len, raw_len = _BLK.unpack(bh)
                payload = f.read(comp_len)
                if len(payload) < comp_len:
                    return None
                block = (payload if comp_len == raw_len
                         else _decompress_block(codec, payload, raw_len))
                pos = bi * block_raw
                lo = max(0, offset - pos)
                hi = min(raw_len, end - pos)
                out += block[lo:hi]
            return bytes(out)
    except (OSError, ValueError, RuntimeError):
        return None
